"""The distributed array (ds-array) — dislib_tpu's single data structure.

Reference capability (SURVEY.md §3.1, `dislib/data/array.py :: class Array`):
a dense or sparse 2-D matrix partitioned into a grid of rectangular blocks,
each block a NumPy/CSR chunk held as a PyCOMPSs future; block-level ops are
``@task`` functions and nothing computes until an explicit sync
(``collect()`` / ``compss_wait_on``).

TPU-native redesign — NOT a block-of-futures translation:

- The whole matrix is ONE global :class:`jax.Array`, laid out on the library
  mesh with ``NamedSharding(P('rows', 'cols'))``.  Placement, inter-device
  movement and overlap come from XLA SPMD + async dispatch, which already
  plays the role the COMPSs task graph plays for the reference (SURVEY.md §8
  "Design stance").
- The reference's irregular top-left block / arbitrary ``block_size`` becomes
  *pad-and-mask metadata*: ``_data`` is padded so every dimension is a
  multiple of the mesh pad quantum, and the region outside the logical
  ``shape`` is ALWAYS ZERO.  That invariant makes contractions (matmul, sum,
  norm) correct with no masking, while min/max/mean mask or rescale
  explicitly.  Ops that could make padding non-zero re-zero it.
- ``block_size`` survives as a *hint* (`_reg_shape`) for API parity and for
  algorithms whose blocking is semantic (QR panels, tsQR tree arity); it no
  longer dictates physical layout — XLA tiles for the MXU itself.
- The "cheap to build, pay on sync" contract (SURVEY.md §4.6) is preserved by
  JAX's async dispatch: every method returns immediately with a live
  ``jax.Array``; ``collect()`` is the only host sync.
- **Dispatch fusion** (round-7 perf PR): op chains don't even dispatch
  per-op.  Elementwise ops, transpose, basic slicing, reductions,
  ``math.matmul`` and ``ops.distances_sq`` build a small deferred
  expression (:class:`_LazyExpr`); the first host-forcing access
  (``collect()``, ``force()``, any internal ``_data`` read, ``float()``,
  a snapshot fetch) compiles and runs the WHOLE chain as ONE cached XLA
  program (``_exec_program``).  On a backend whose per-dispatch host RTT
  is ~70 ms (BENCH_local_r05), a k-op chain costs one RTT instead of k.
  ``DSLIB_EAGER=1`` restores per-op dispatch for debugging, and chains
  force themselves after ``DSLIB_FUSION_CAP`` nodes (default 96) so a
  long Python loop cannot build an unboundedly large program.  Fused and
  eager paths share the same op bodies, so results match bit-for-bit up
  to XLA's in-program excess-precision FMA contraction (≤ 1 ulp; see
  ``_exec_program``) — pinned by ``tests/test_fusion.py``.

Sparse support: ``_sparse=True`` arrays keep a BCOO backing for memory-honest
storage where it pays (see `dislib_tpu/data/sparse.py`), with a dense+mask
fallback — the decision recorded per estimator as SURVEY §8 directs.
"""

from __future__ import annotations

import math
import os
from functools import partial
from numbers import Number

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.ops.base import distances_sq as _raw_distances_sq, precise
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils.profiling import profiled_jit as _pjit

__all__ = [
    "Array",
    "array",
    "random_array",
    "zeros",
    "full",
    "ones",
    "identity",
    "eye",
    "apply_along_axis",
    "concat_rows",
    "concat_cols",
    "rechunk",
    "ensure_canonical",
]


# ---------------------------------------------------------------------------
# padding helpers
# ---------------------------------------------------------------------------

def _padded_dim(n: int, quantum: int) -> int:
    return max(quantum, int(math.ceil(n / quantum)) * quantum)


def _padded_shape(shape, quantum):
    return tuple(_padded_dim(int(s), quantum) for s in shape)


def _pad_mask(padded_shape, logical_shape, dtype=jnp.bool_):
    """Boolean mask: True inside the logical region."""
    r = lax.broadcasted_iota(jnp.int32, padded_shape, 0) < logical_shape[0]
    c = lax.broadcasted_iota(jnp.int32, padded_shape, 1) < logical_shape[1]
    return (r & c).astype(dtype)


def _zero_pad(data, logical_shape):
    """Force the padding region to zero (the core Array invariant)."""
    if data.shape == tuple(logical_shape):
        return data
    return jnp.where(_pad_mask(data.shape, logical_shape), data, jnp.zeros((), data.dtype))


@partial(_pjit, static_argnames=("padded_shape", "logical_shape"),
         name="place")
def _place(data, padded_shape, logical_shape):
    """Pad `data` (logical region) up to padded_shape with zeros."""
    out = jnp.zeros(padded_shape, data.dtype)
    out = lax.dynamic_update_slice(out, data.astype(out.dtype), (0, 0))
    del logical_shape
    return out


def _default_block_size(shape, mesh):
    r, c = _mesh.mesh_shape(mesh)
    return (max(1, -(-shape[0] // r)), max(1, -(-shape[1] // c)))


# ---------------------------------------------------------------------------
# dispatch fusion: the lazy expression layer
# ---------------------------------------------------------------------------

def _eager_mode() -> bool:
    """True when DSLIB_EAGER=1 — every op dispatches its own XLA program
    (the pre-fusion behavior; the debugging escape hatch)."""
    return os.environ.get("DSLIB_EAGER", "0") not in ("", "0")


def _fusion_cap() -> int:
    """Max deferred nodes per chain before an automatic force — bounds
    both compile time and the linearizer's recursion depth."""
    return int(os.environ.get("DSLIB_FUSION_CAP", "96"))


class _LazyExpr:
    """One deferred op: ``op`` names an entry in ``_INSTRS``, ``static``
    is its hashable config (shapes, op variants), ``args`` are child
    ``_LazyExpr`` nodes or concrete ``jax.Array``/ndarray leaves.
    ``pshape``/``dtype`` are the padded output shape and dtype, computed
    at build time so ``Array`` metadata never forces the chain.

    ``refs`` counts consumers (parent nodes + wrapping Arrays).  A node
    with ``refs > 1`` is a shared prefix: the force that first reaches it
    emits it as an extra program output and caches it in ``value``, so
    every other consumer linearizes it as a LEAF instead of re-running
    (and re-compiling) the whole prefix per fan-out branch."""

    __slots__ = ("op", "static", "args", "pshape", "dtype", "n_ops",
                 "refs", "value")

    def __init__(self, op, static, args, pshape, dtype):
        self.op = op
        self.static = static
        self.args = args
        self.pshape = tuple(int(s) for s in pshape)
        self.dtype = jnp.dtype(dtype)
        self.refs = 0
        self.value = None
        self.n_ops = 1
        for a in args:
            if isinstance(a, _LazyExpr):
                a.refs += 1
                self.n_ops += a.n_ops


def _linearize(root: _LazyExpr):
    """Postorder program for one chain: ``(instrs, leaves, shared)``.

    Each instruction is ``(op, static, srcs)`` with a src of
    ``(0, leaf_idx)`` or ``(1, instr_idx)``; the program's trailing
    element is the tuple of instr indices to RETURN alongside the root —
    the shared (refs > 1) interior nodes, listed in ``shared`` so the
    caller can backfill their ``value`` caches.  Shared subexpressions
    and repeated leaves dedupe by identity, valued nodes load as leaves,
    so diamond graphs and cross-Array fan-outs evaluate once."""
    instrs, leaves, shared = [], [], []
    instr_memo, leaf_memo = {}, {}

    def visit(node):
        if isinstance(node, _LazyExpr) and node.value is None:
            slot = instr_memo.get(id(node))
            if slot is None:
                srcs = tuple(visit(a) for a in node.args)
                instrs.append((node.op, node.static, srcs))
                slot = (1, len(instrs) - 1)
                instr_memo[id(node)] = slot
                if node.refs > 1 and node is not root:
                    shared.append((node, len(instrs) - 1))
            return slot
        if isinstance(node, _LazyExpr):
            node = node.value           # materialised prefix → plain leaf
        slot = leaf_memo.get(id(node))
        if slot is None:
            leaves.append(node)
            slot = (0, len(leaves) - 1)
            leaf_memo[id(node)] = slot
        return slot

    visit(root)
    program = tuple(instrs) + (tuple(idx for _, idx in shared),)
    return program, leaves, [node for node, _ in shared]


def _place_region(v, pshape):
    """Traced analog of `_repad`'s place+reshard: zero canvas, write the
    logical region at (0, 0), constrain to the library sharding."""
    if tuple(v.shape) != tuple(pshape):
        canvas = jnp.zeros(pshape, v.dtype)
        v = lax.dynamic_update_slice(canvas, v, (0, 0))
    return lax.with_sharding_constraint(v, _mesh.data_sharding())


def _matmul_body(a, b, ta, tb, policy=None):
    """The ONE GEMM body shared by the eager `math.matmul` kernel and the
    fused "matmul" instruction (zero padding ⇒ padded == logical dot).
    ``policy`` is a precision policy (None → float32-faithful): the
    contraction runs at the policy's compute dtype with f32 accumulation
    (`ops/precision.pdot`)."""
    from dislib_tpu.ops import precision as px
    if ta:
        a = a.T
    if tb:
        b = b.T
    out = px.pdot(a, b, policy if policy is not None else px.FLOAT32)
    return lax.with_sharding_constraint(out, _mesh.data_sharding())


def _instr_ew2(static, a, b):
    op, a_shape, b_shape, out_shape = static
    return _ew_array_body(a, b, a_shape, b_shape, out_shape, op)


def _instr_ew1(static, a, scalar):
    op, shape = static
    return _ew_scalar_body(a, scalar, shape, op)


def _instr_transpose(static, a):
    del static
    return lax.with_sharding_constraint(a.T, _mesh.data_sharding())


def _instr_slice(static, a):
    r0, r1, rs, c0, c1, cs, out_shape, out_pshape = static
    del out_shape
    return _place_region(a[r0:r1:rs, c0:c1:cs], out_pshape)


def _instr_reduce(static, a):
    kind, axis, in_shape, out_shape, out_pshape = static
    red = _reduce_body(a, in_shape, kind, axis)
    return _place_region(red[: out_shape[0], : out_shape[1]], out_pshape)


def _instr_matmul(static, a, b):
    ta, tb, policy_name = static
    from dislib_tpu.ops import precision as px
    inner_a = a.shape[0] if ta else a.shape[1]
    inner_b = b.shape[1] if tb else b.shape[0]
    pad_to = max(inner_a, inner_b)
    if inner_a < pad_to:                 # quantum mismatch: grow the pad
        grow = pad_to - inner_a
        a = jnp.pad(a, ((0, grow), (0, 0)) if ta else ((0, 0), (0, grow)))
    if inner_b < pad_to:
        grow = pad_to - inner_b
        b = jnp.pad(b, ((0, 0), (0, grow)) if tb else ((0, grow), (0, 0)))
    return _matmul_body(a, b, ta, tb, px.of_name(policy_name))


def _instr_dist(static, a, b):
    a_shape, b_shape, out_pshape, prec = static
    (m, n), (k, _) = a_shape, b_shape
    d = _raw_distances_sq(a[:m, :n], b[:k, :n], precision=prec)
    return _place_region(d, out_pshape)


def _instr_rechunk(static, a):
    """Round-11 rechunk PR: re-quantize a backing to a new padded canvas
    INSIDE the fused program (crop/place, re-zero outside the logical
    region, constrain to the canonical sharding) — a mid-chain reshard
    costs zero extra dispatches.  Body shared with the eager collective
    paths in ``ops/rechunk.py``.  The trailing static element is the
    mesh token: it rides the program cache key so a mesh switch that
    happens to preserve every shape (e.g. (4,2) → (2,4), same quantum)
    retraces instead of replaying a constraint to the OLD mesh."""
    from dislib_tpu.ops.rechunk import requantize_body
    logical_shape, out_pshape, _mesh_token = static
    return requantize_body(a, logical_shape, out_pshape)


def _mesh_token():
    """Hashable identity of the current default mesh (shape + device
    ids) — the cache-key ingredient for mesh-sensitive fused statics."""
    mesh = _mesh.get_mesh()
    return (tuple(mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


def _instr_kernel(static, *args):
    """Round-9 serving PR: an arbitrary traced kernel body as ONE fusion
    node.  ``static`` is ``(body, cfg)``: ``body`` a module-level pure
    function (hashable by identity, stable across calls — a lambda or
    closure would defeat both the jit cache and the fusion-program
    dedup), ``cfg`` its hashable config tuple.  The body receives the
    PADDED operand arrays exactly as the graph stores them and must
    return an array of the declared padded output shape with the
    region outside the logical shape zeroed (the Array invariant) —
    estimator predict kernels already satisfy this, which is what lets
    a whole scaler → estimator → argmax pipeline linearize into one
    cached XLA program."""
    body, cfg = static
    return body(cfg, *args)


_INSTRS = {
    "ew2": _instr_ew2,
    "ew1": _instr_ew1,
    "transpose": _instr_transpose,
    "slice": _instr_slice,
    "reduce": _instr_reduce,
    "matmul": _instr_matmul,
    "dist": _instr_dist,
    "kernel": _instr_kernel,
    "rechunk": _instr_rechunk,
}


def fused_kernel(body, cfg, args, out_shape, dtype, out_pshape=None,
                 reg_shape=None, sparse=False):
    """Defer ``body(cfg, *operands)`` as a fusion-graph node and wrap it
    as an :class:`Array` — the estimator-predict entry into the dispatch
    fusion layer (round-9 serving PR).

    ``body`` must be a module-level traced function taking its hashable
    ``cfg`` tuple first, then one padded device array per entry of
    ``args`` (each an :class:`Array`, a deferred node via
    ``arr._node()``, or a concrete ``jax.Array``/ndarray leaf such as
    model parameters).  It must return the padded ``out_pshape`` result,
    zero outside ``out_shape``.  Under ``DSLIB_EAGER=1`` the node is
    forced immediately — the same single-instruction program runs as its
    own dispatch, preserving per-op debugging semantics."""
    if out_pshape is None:
        out_pshape = _padded_shape(out_shape, _mesh.pad_quantum())
    ops = tuple(a._node() if isinstance(a, Array) else a for a in args)
    expr = _LazyExpr("kernel", (body, tuple(cfg)), ops,
                     tuple(out_pshape), dtype)
    arr = _lazy_array(expr, out_shape, reg_shape, sparse)
    if _eager_mode():
        arr.force()
    return arr


@partial(_pjit, static_argnames=("program",), name="fused_chain")
@precise
def _exec_program(program, *operands):
    """Interpret one linearized chain while tracing — the whole program
    compiles (and caches) as ONE XLA executable keyed on (program,
    operand shapes/dtypes).

    Numerics vs the eager path: instruction bodies are shared verbatim,
    so every individual op rounds identically.  The ONE divergence XLA
    is permitted is excess-precision contraction WITHIN the fused
    program (a multiply feeding an add on the same element may become a
    single FMA — ≤ 1 ulp, and strictly more accurate).  Neither
    `optimization_barrier` nor an f32→f32 `reduce_precision` stops the
    backend's fp-contract inside one fused kernel (measured on XLA:CPU,
    jaxlib 0.4.36), and the global `--xla_allow_excess_precision=false`
    escape would mutate user-scope flags — so the contract is: bit-equal
    except mul→add contraction, bounded by 1 ulp
    (`tests/test_fusion.py::test_fma_contraction_is_the_only_divergence`)."""
    *instrs, shared_out = program
    vals = []
    for op, static, srcs in instrs:
        args = [operands[i] if kind == 0 else vals[i] for kind, i in srcs]
        vals.append(_INSTRS[op](static, *args))
    # root first, then each shared interior node (cached by the caller so
    # other fan-out consumers load it as a leaf instead of re-running it)
    return (vals[-1],) + tuple(vals[i] for i in shared_out)


def _unique_ops(expr: _LazyExpr) -> int:
    """Exact deferred-node count of a DAG (``n_ops`` overcounts shared
    subexpressions — exponentially so for diamond towers)."""
    seen, stack = set(), [expr]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.extend(a for a in node.args if isinstance(a, _LazyExpr))
    return len(seen)


def _lazy_array(expr, shape, reg_shape, sparse):
    """Wrap a deferred node; force automatically past the fusion cap.
    ``n_ops`` is a cheap upper bound — only when it crosses the cap is
    the exact (deduped) count walked, so shared-subexpression DAGs are
    not forced early by the overcount."""
    arr = Array(expr, shape, reg_shape=reg_shape, sparse=sparse)
    if expr.n_ops >= _fusion_cap() and _unique_ops(expr) >= _fusion_cap():
        arr.force()
    return arr


def _ew_dtype(op, da, db):
    """Result dtype of a deferred binary op (metadata only — the traced
    body performs the real promotion; this mirrors it)."""
    dt = jnp.promote_types(da, db)
    # true division / exp / sqrt of integer operands float their result
    if op in ("div", "rdiv", "exp_", "sqrt_") \
            and jnp.issubdtype(dt, jnp.integer):
        dt = jnp.dtype(jnp.float64 if jax.config.jax_enable_x64
                       else jnp.float32)
    return dt


def _reduce_dtype(kind, dtype):
    if kind in ("mean", "norm"):
        return jnp.promote_types(dtype, jnp.float32)
    return jnp.dtype(dtype)


def _array_distances(a: "Array", b: "Array", precision=None) -> "Array":
    """ds-array pairwise squared distances — a fusable graph node (or one
    eager kernel under DSLIB_EAGER); see `ops.base.distances_sq`."""
    if a.shape[1] != b.shape[1]:
        raise ValueError(f"distances_sq: feature dims differ "
                         f"({a.shape[1]} vs {b.shape[1]})")
    out_shape = (a.shape[0], b.shape[0])
    out_pshape = _padded_shape(out_shape, _mesh.pad_quantum())
    dtype = jnp.promote_types(a.dtype, b.dtype)
    if _eager_mode():
        data = _distances_op(a._data, b._data, a._shape, b._shape,
                             out_pshape, precision)
        return Array(data, out_shape, None, False)
    expr = _LazyExpr("dist", (a._shape, b._shape, out_pshape, precision),
                     (a._node(), b._node()), out_pshape, dtype)
    return _lazy_array(expr, out_shape, None, False)


@partial(_pjit, static_argnames=("a_shape", "b_shape", "out_pshape", "prec"),
         name="distances")
@precise
def _distances_op(a, b, a_shape, b_shape, out_pshape, prec):
    return _instr_dist((a_shape, b_shape, out_pshape, prec), a, b)


# ---------------------------------------------------------------------------
# the Array
# ---------------------------------------------------------------------------

class Array:
    """A 2-D matrix sharded over the device mesh.

    Parameters are internal; users build Arrays with :func:`array`,
    :func:`random_array`, the loaders in :mod:`dislib_tpu.data.io`, or as
    results of dislib_tpu operations.
    """

    def __init__(self, data, shape, reg_shape=None, sparse=False,
                 _skip_zero_check=True):
        # data: padded, zero-outside-logical — either a concrete jax.Array
        # or a deferred _LazyExpr (the fusion layer)
        if isinstance(data, _LazyExpr):
            data.refs += 1              # this wrapper is a consumer too
            self._lazy = data
            self._concrete = None
        else:
            self._concrete = data
            self._lazy = None
        self._shape = (int(shape[0]), int(shape[1]))
        if reg_shape is None:
            reg_shape = _default_block_size(self._shape, None)
        self._reg_shape = (int(reg_shape[0]), int(reg_shape[1]))
        self._sparse = bool(sparse)

    # -- fusion plumbing -----------------------------------------------------

    @property
    def _data(self):
        """The padded device backing.  Reading it is a FORCE point: any
        deferred op chain compiles and runs as one program first."""
        if self._concrete is None:
            expr = self._lazy
            if expr.value is not None:   # prefix already materialised by
                self._concrete = expr.value  # another consumer's force
            else:
                program, leaves, shared = _linearize(expr)
                root, *shared_vals = _exec_program(program, *leaves)
                for node, val in zip(shared, shared_vals):
                    node.value = val
                    node.args = ()      # edges are dead once cached —
                expr.value = root       # don't pin the leaf buffers
                expr.args = ()
                self._concrete = root
            self._lazy = None
        return self._concrete

    def _node(self):
        """This array as a fusion-graph operand: its deferred expression
        if one is pending, else the concrete backing as a leaf."""
        return self._lazy if self._lazy is not None else self._concrete

    @property
    def _pshape(self) -> tuple[int, int]:
        """Padded backing shape — available without forcing."""
        if self._lazy is not None:
            return self._lazy.pshape
        return tuple(self._concrete.shape)

    @property
    def is_lazy(self) -> bool:
        """True while this array is an unforced deferred op chain."""
        return self._concrete is None

    def force(self) -> "Array":
        """Materialise any deferred op chain as ONE compiled dispatch and
        return self.  A no-op on an already-concrete array.  `collect()`,
        `float()`, snapshot fetches, and every internal `_data` read
        force implicitly; call this to place the sync point explicitly."""
        self._data  # noqa: B018 — property access runs the fused program
        return self

    # -- construction helpers ------------------------------------------------

    @classmethod
    def _from_logical(cls, data: jax.Array, reg_shape=None, sparse=False) -> "Array":
        """Wrap a logically-shaped (unpadded) device/host array."""
        shape = data.shape
        q = _mesh.pad_quantum()
        pshape = _padded_shape(shape, q)
        if tuple(shape) != pshape:
            data = _place(data, pshape, tuple(shape))
        data = jax.device_put(data, _mesh.data_sharding())
        return cls(data, shape, reg_shape=reg_shape, sparse=sparse)

    # -- metadata ------------------------------------------------------------

    @property
    def shape(self) -> tuple[int, int]:
        return self._shape

    @property
    def dtype(self):
        if self._lazy is not None:       # metadata — must not force
            return self._lazy.dtype
        return self._concrete.dtype

    @property
    def _n_blocks(self) -> tuple[int, int]:
        return (-(-self._shape[0] // self._reg_shape[0]),
                -(-self._shape[1] // self._reg_shape[1]))

    @property
    def block_size(self) -> tuple[int, int]:
        return self._reg_shape

    def __repr__(self):
        return (f"dslib.Array(shape={self._shape}, block_size={self._reg_shape}, "
                f"dtype={self.dtype}, sparse={self._sparse})")

    # -- sync points ---------------------------------------------------------

    def collect(self) -> np.ndarray:
        """Materialise on host — the analog of compss_wait_on + merge (SURVEY §4.6).

        Multi-host jobs: a row-sharded global array spans non-addressable
        devices, so the gather is a `process_allgather` over DCN (every
        host ends with the full logical array, the reference's
        gather-to-master contract)."""
        from dislib_tpu.utils.profiling import count_transfer
        count_transfer()
        if not self._data.is_fully_addressable:
            from jax.experimental import multihost_utils
            out = np.asarray(multihost_utils.process_allgather(
                self._data, tiled=True))
        else:
            out = np.asarray(jax.device_get(self._data))
        out = out[: self._shape[0], : self._shape[1]]
        if self._sparse:
            import scipy.sparse as sp
            return sp.csr_matrix(out)
        return out

    def block_until_ready(self) -> "Array":
        self._data.block_until_ready()
        return self

    def __float__(self) -> float:
        """Host scalar of a (1, 1) array — a force point (the deferred
        chain runs as one program first)."""
        if self._shape != (1, 1):
            raise TypeError(
                f"only a (1, 1) ds-array converts to float, got {self._shape}")
        # read the backing directly: collect() of a sparse-flagged array
        # wraps the scalar in a csr_matrix, which float() rejects
        from dislib_tpu.utils.profiling import count_transfer
        count_transfer()
        return float(np.asarray(jax.device_get(self._data[0:1, 0:1]))
                     .reshape(()))

    # -- layout --------------------------------------------------------------

    def rechunk(self, block_size) -> "Array":
        """Change the block-size hint — and, when the backing was laid out
        under a DIFFERENT mesh quantum (elastic mesh change), reshard it
        on-device for the current mesh via :func:`rechunk` (round-11
        collective-rechunk PR).  On an already-canonical backing this
        stays metadata-only, the reference's data-movement rechunk
        (SURVEY §3.1) collapsed to a no-op on a global jax.Array."""
        return rechunk(self, block_size)

    def astype(self, dtype) -> "Array":
        return Array(self._data.astype(dtype), self._shape, self._reg_shape, self._sparse)

    def copy(self) -> "Array":
        return Array(self._data, self._shape, self._reg_shape, self._sparse)

    # -- transpose -----------------------------------------------------------

    def transpose(self) -> "Array":
        shape = (self._shape[1], self._shape[0])
        reg = (self._reg_shape[1], self._reg_shape[0])
        if _eager_mode():
            data = _transpose_op(self._data, self._shape)
            return Array._from_logical_padded(data, shape, reg, self._sparse)
        pshape = self._pshape
        expr = _LazyExpr("transpose", (self._shape,), (self._node(),),
                         (pshape[1], pshape[0]), self.dtype)
        return _lazy_array(expr, shape, reg, self._sparse)

    @property
    def T(self) -> "Array":
        return self.transpose()

    @classmethod
    def _from_logical_padded(cls, padded_data, shape, reg_shape=None, sparse=False):
        """Wrap data already padded+zeroed for `shape`."""
        padded_data = jax.device_put(padded_data, _mesh.data_sharding())
        return cls(padded_data, shape, reg_shape=reg_shape, sparse=sparse)

    # -- elementwise ---------------------------------------------------------

    def _coerce(self, other):
        if isinstance(other, Array):
            if other._shape != self._shape:
                # allow (1, n) / (n, 1) broadcasting
                if not _broadcastable(other._shape, self._shape):
                    raise ValueError(f"shape mismatch {self._shape} vs {other._shape}")
            return other
        if isinstance(other, Number):
            return other
        return NotImplemented

    def _ew(self, other, op):
        other = self._coerce(other)
        if other is NotImplemented:
            return NotImplemented
        if isinstance(other, Array):
            out_shape = _broadcast_shape(self._shape, other._shape)
            sparse = self._sparse and other._sparse
            if _eager_mode():
                data = _ew_array_op(self._data, other._data, self._shape,
                                    other._shape, out_shape, op)
                return Array(data, out_shape, self._reg_shape, sparse)
            pa, pb = self._pshape, other._pshape
            expr = _LazyExpr(
                "ew2", (op, self._shape, other._shape, out_shape),
                (self._node(), other._node()),
                (max(pa[0], pb[0]), max(pa[1], pb[1])),
                _ew_dtype(op, self.dtype, other.dtype))
            return _lazy_array(expr, out_shape, self._reg_shape, sparse)
        scalar = float(other) if not isinstance(other, bool) else other
        # scalar mul/div/pow and the zero-preserving unaries map zeros to
        # zeros; add/sub of a nonzero scalar destroys sparsity (the flag
        # is metadata — data is dense).  exp is NOT zero-preserving
        # (exp(0)=1 densifies) — its dummy 0.0 operand must not slip it
        # through the ==0.0 clause.
        if op == "exp_":
            preserves = False
        else:
            preserves = op in ("mul", "div", "pow", "abs_", "sqrt_") \
                or float(other) == 0.0
        sparse = self._sparse and preserves
        if _eager_mode():
            data = _ew_scalar_op(self._data, scalar, self._shape, op)
            return Array(data, self._shape, self._reg_shape, sparse)
        # the scalar rides as a traced leaf (pre-rounded to this array's
        # dtype, as the eager kernel does) so new values never retrace;
        # the metadata dtype mirrors the body's promotion on SAME-dtype
        # operands (int/scalar true-division still floats, e.g.)
        leaf = np.asarray(scalar, np.dtype(self.dtype))
        expr = _LazyExpr("ew1", (op, self._shape),
                         (self._node(), leaf), self._pshape,
                         _ew_dtype(op, self.dtype, self.dtype))
        return _lazy_array(expr, self._shape, self._reg_shape, sparse)

    def __add__(self, o):  return self._ew(o, "add")
    def __radd__(self, o): return self._ew(o, "add")
    def __sub__(self, o):  return self._ew(o, "sub")
    def __rsub__(self, o): return self._ew(o, "rsub")
    def __mul__(self, o):  return self._ew(o, "mul")
    def __rmul__(self, o): return self._ew(o, "mul")
    def __truediv__(self, o):  return self._ew(o, "div")
    def __rtruediv__(self, o): return self._ew(o, "rdiv")
    def __pow__(self, o):  return self._ew(o, "pow")
    def __neg__(self):     return self._ew(-1.0, "mul")

    def __abs__(self):
        return self._ew(0.0, "abs_")

    def sqrt(self) -> "Array":
        return self._ew(0.0, "sqrt_")

    def exp(self) -> "Array":
        return self._ew(0.0, "exp_")

    # -- matmul --------------------------------------------------------------

    def __matmul__(self, other):
        from dislib_tpu.math import matmul
        return matmul(self, other)

    # -- reductions ----------------------------------------------------------

    def _reduce(self, kind: str, axis=0):
        if axis not in (0, 1, None):
            raise ValueError("axis must be 0, 1 or None")
        if axis is None:
            shape = (1, 1)
        elif axis == 0:
            shape = (1, self._shape[1])
        else:
            shape = (self._shape[0], 1)
        if _eager_mode():
            data = _reduce_op(self._data, self._shape, kind, axis)
            return Array._from_logical_padded(_repad(data, shape), shape,
                                              None, False)
        out_pshape = _padded_shape(shape, _mesh.pad_quantum())
        expr = _LazyExpr("reduce", (kind, axis, self._shape, shape,
                                    out_pshape),
                         (self._node(),), out_pshape,
                         _reduce_dtype(kind, self.dtype))
        return _lazy_array(expr, shape, None, False)

    def sum(self, axis=0):  return self._reduce("sum", axis)
    def mean(self, axis=0): return self._reduce("mean", axis)
    def min(self, axis=0):  return self._reduce("min", axis)
    def max(self, axis=0):  return self._reduce("max", axis)

    def norm(self, axis=0):
        return self._reduce("norm", axis)

    # -- indexing ------------------------------------------------------------

    def __getitem__(self, key):
        rows, cols = _split_key(key)
        r_idx, r_len = _normalize_index(rows, self._shape[0])
        c_idx, c_len = _normalize_index(cols, self._shape[1])
        new_shape = (r_len, c_len)
        if not _eager_mode() and isinstance(r_idx, slice) \
                and isinstance(c_idx, slice):
            # basic (int/slice) indexing stays on the fusion graph; fancy
            # indexing below forces — its gather shapes are data-sized
            out_pshape = _padded_shape(new_shape, _mesh.pad_quantum())
            expr = _LazyExpr(
                "slice", (r_idx.start, r_idx.stop, r_idx.step,
                          c_idx.start, c_idx.stop, c_idx.step,
                          new_shape, out_pshape),
                (self._node(),), out_pshape, self.dtype)
            return _lazy_array(expr, new_shape, None, self._sparse)
        data = _gather_op(self._data, r_idx, c_idx)
        return Array._from_logical_padded(_repad(data, new_shape), new_shape,
                                          None, self._sparse)

    # -- iteration over logical blocks (parity: Array._iterator) -------------

    def iterator(self, axis=0):
        """Yield row-block (axis=0) or col-block (axis=1) sub-arrays, one per
        `block_size` stripe — reference `Array._iterator` (SURVEY §3.1).

        Stripes are cheap contiguous slices of the padded backing (lax.slice
        + repad), not general gathers — each yield costs one slice op."""
        n = self._shape[axis]
        step = self._reg_shape[axis]
        m, c = self._shape
        for start in range(0, n, step):
            stop = min(start + step, n)
            if axis == 0:
                logical = self._data[start:stop, :c]
                shape = (stop - start, c)
            else:
                logical = self._data[:m, start:stop]
                shape = (m, stop - start)
            yield Array._from_logical_padded(_repad(logical, shape), shape,
                                             None, self._sparse)


def _broadcastable(a, b):
    return all(x == y or x == 1 or y == 1 for x, y in zip(a, b))


def _broadcast_shape(a, b):
    return tuple(max(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# op bodies + jitted kernels (module-level so jit caches by shape).  Each
# body is shared VERBATIM by its eager kernel and the fused-program
# instruction, so DSLIB_EAGER=1 results bit-match the fused path.
# ---------------------------------------------------------------------------

_BINOPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "rsub": lambda a, b: b - a,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "rdiv": lambda a, b: b / a,
    "pow": lambda a, b: a ** b,
    "exp_": lambda a, b: jnp.exp(a),
    "abs_": lambda a, b: jnp.abs(a),
    "sqrt_": lambda a, b: jnp.sqrt(a),
}


def _ew_array_body(a, b, a_shape, b_shape, out_shape, op):
    # crop each operand to its logical region, broadcast, then re-pad. The
    # crop/pad pair fuses to a masked op under XLA; it keeps broadcasting
    # semantics exact when a (1, n) operand's padded rows would otherwise
    # collide with the other operand's rows.
    av = a[: a_shape[0], : a_shape[1]]
    bv = b[: b_shape[0], : b_shape[1]]
    out = _BINOPS[op](av, bv)
    res = jnp.zeros(_padded_shape_like(a, b, out_shape), out.dtype)
    res = lax.dynamic_update_slice(res, out, (0, 0))
    return res


@partial(_pjit, static_argnames=("a_shape", "b_shape", "out_shape", "op"),
         name="ew_array")
def _ew_array_op(a, b, a_shape, b_shape, out_shape, op):
    return _ew_array_body(a, b, a_shape, b_shape, out_shape, op)


def _padded_shape_like(a, b, out_shape):
    # the padded canvas big enough for out_shape under the current quantum
    q_r = max(a.shape[0], b.shape[0])
    q_c = max(a.shape[1], b.shape[1])
    # out_shape is the broadcast of the logical shapes; the matching padded
    # canvas is the max of operand canvases in each dim.
    return (q_r, q_c)


def _ew_scalar_body(a, scalar, shape, op):
    out = _BINOPS[op](a, jnp.asarray(scalar, a.dtype))
    return _zero_pad(out, shape)


@partial(_pjit, static_argnames=("shape", "op"), name="ew_scalar")
def _ew_scalar_op(a, scalar, shape, op):
    return _ew_scalar_body(a, scalar, shape, op)


@partial(_pjit, static_argnames=("shape",), name="transpose")
def _transpose_op(a, shape):
    return a.T


def _reduce_body(a, shape, kind, axis):
    mask = _pad_mask(a.shape, shape)
    if kind in ("sum", "norm", "mean"):
        x = jnp.where(mask, a, 0)
        if kind == "norm":
            x = x * x
        red = jnp.sum(x, axis=axis, keepdims=True) if axis is not None else \
            jnp.sum(x, keepdims=True).reshape(1, 1)
        if kind == "mean":
            n = shape[axis] if axis is not None else shape[0] * shape[1]
            red = red / n
        if kind == "norm":
            red = jnp.sqrt(red)
    else:
        fill = jnp.asarray(jnp.inf if kind == "min" else -jnp.inf, a.dtype)
        x = jnp.where(mask, a, fill)
        fn = jnp.min if kind == "min" else jnp.max
        red = fn(x, axis=axis, keepdims=True) if axis is not None else \
            fn(x, keepdims=True).reshape(1, 1)
    return red


@partial(_pjit, static_argnames=("shape", "kind", "axis"), name="reduce")
def _reduce_op(a, shape, kind, axis):
    return _reduce_body(a, shape, kind, axis)


def _repad(logical_data, shape):
    """Pad logical(-region) data out to the current quantum and zero-fill."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    cropped = logical_data[: shape[0], : shape[1]]
    if cropped.shape == pshape:
        return jax.device_put(cropped, _mesh.data_sharding())
    out = _place(cropped, pshape, shape)
    return jax.device_put(out, _mesh.data_sharding())


def _gather_op(a, r_idx, c_idx):
    if isinstance(r_idx, slice) and isinstance(c_idx, slice):
        return a[r_idx, c_idx]
    if isinstance(r_idx, slice):
        return a[r_idx, :][:, c_idx]
    if isinstance(c_idx, slice):
        return a[r_idx, :][:, c_idx]
    return a[r_idx, :][:, c_idx]


def _split_key(key):
    if isinstance(key, tuple):
        if len(key) != 2:
            raise IndexError("ds-arrays are 2-D: index with at most two axes")
        return key
    return key, slice(None)


def _normalize_index(idx, dim):
    """Return (index object over the padded array, result length)."""
    if isinstance(idx, (int, np.integer)):
        i = int(idx)
        if i < 0:
            i += dim
        if not 0 <= i < dim:
            raise IndexError(f"index {idx} out of bounds for dim {dim}")
        return slice(i, i + 1), 1
    if isinstance(idx, slice):
        start, stop, step = idx.indices(dim)
        if step <= 0:
            raise IndexError("negative slice steps not supported")
        length = max(0, -(-(stop - start) // step))
        return slice(start, stop, step), length
    # fancy indexing with a list / ndarray of ints (or bools)
    arr = np.asarray(idx)
    if arr.dtype == bool:
        if arr.shape[0] != dim:
            raise IndexError("boolean index length mismatch")
        arr = np.nonzero(arr)[0]
    if arr.size and not np.issubdtype(arr.dtype, np.integer):
        # silent float→int truncation would index the wrong rows; an empty
        # selection (np.asarray([]) is float64) stays valid, as in NumPy
        raise IndexError(f"fancy index must be integer or boolean, got "
                         f"dtype {arr.dtype}")
    arr = arr.astype(np.int64)
    arr = np.where(arr < 0, arr + dim, arr)
    if arr.size and (arr.min() < 0 or arr.max() >= dim):
        raise IndexError("fancy index out of bounds")
    return arr, int(arr.shape[0])


# ---------------------------------------------------------------------------
# public constructors  (parity: dislib.data.array constructors, SURVEY §3.1)
# ---------------------------------------------------------------------------

def array(x, block_size=None, dtype=None) -> Array:
    """Build a ds-array from host data (ndarray, nested lists, or scipy sparse).

    ``dtype=None`` keeps the TPU-native float32 default but WARNS once when
    that silently narrows float64 input (the reference's blocks are NumPy
    float64 — a port should not change precision silently).  Pass an
    explicit ``dtype=`` to silence the warning; ``dtype=np.float64`` is
    honoured when JAX x64 mode is enabled (CPU rig) and raises a clear
    error otherwise."""
    import scipy.sparse as sp
    sparse = sp.issparse(x)
    if sparse:
        x = x.toarray()
    on_device = isinstance(x, jax.Array)
    if not on_device:
        x = np.asarray(x)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if x.ndim != 2:
        raise ValueError("ds-arrays are 2-dimensional")
    if on_device:
        # device input: same dtype policy, applied without a host round-trip
        x = _coerce_dtype(x, dtype)
    else:
        x = jnp.asarray(_coerce_dtype(x, dtype))
    if block_size is None:
        block_size = _default_block_size(x.shape, None)
    block_size = _check_block_size(x.shape, block_size)
    return Array._from_logical(x, reg_shape=block_size, sparse=sparse)


def _require_dtype_support(dtype):
    """Reject dtypes the backend would silently narrow (f64 without x64)."""
    if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
        raise ValueError(
            "dtype=float64 requires JAX x64 mode (JAX_ENABLE_X64=1 or "
            "jax.config.update('jax_enable_x64', True)); the TPU-native "
            "default is float32")


def _coerce_dtype(x, dtype):
    """Apply the library dtype policy (see :func:`array`) — the ONE
    implementation, shared by the host (ndarray) and device (jax.Array)
    input paths."""
    if dtype is not None:
        _require_dtype_support(dtype)
        dtype = np.dtype(dtype)
        return x if x.dtype == dtype else x.astype(dtype)
    if x.dtype == np.float64:
        _warn_f64_narrowing()
        return x.astype(np.float32)
    return x


def _warn_f64_narrowing():
    import warnings
    warnings.warn(
        "ds.array received float64 data and is narrowing it to float32 "
        "(the TPU-native default). Pass dtype=np.float32 to silence, or "
        "dtype=np.float64 with JAX x64 mode to keep full precision.",
        UserWarning, stacklevel=4)


def _check_block_size(shape, block_size):
    """Validate and return the effective block size: oversized blocks clamp
    to the logical shape (physical layout is mesh-determined anyway — the
    block size only drives `iterator` stripes and `_reg_shape` metadata)."""
    br, bc = block_size
    if br <= 0 or bc <= 0:
        raise ValueError("block_size entries must be positive")
    return (min(br, shape[0]) if shape[0] > 0 else br,
            min(bc, shape[1]) if shape[1] > 0 else bc)


def random_array(shape, block_size=None, random_state=None,
                 dtype=jnp.float32) -> Array:
    """Uniform [0, 1) ds-array; deterministic per seed, seeded per the whole
    array (the reference seeds per block — an implementation artifact of
    task-parallel generation, not an API contract)."""
    _require_dtype_support(dtype)
    seed = _seed_from(random_state)
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = _random_uniform(jax.random.PRNGKey(seed), pshape,
                           tuple(int(s) for s in shape), np.dtype(dtype).name)
    data = jax.device_put(data, _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


@partial(_pjit, static_argnames=("pshape", "shape", "dtype"),
         name="random_uniform")
def _random_uniform(key, pshape, shape, dtype):
    vals = jax.random.uniform(key, pshape, dtype=dtype)
    return _zero_pad(vals, shape)


def _seed_from(random_state):
    if random_state is None:
        return np.random.randint(0, 2**31 - 1)
    if isinstance(random_state, (int, np.integer)):
        return int(random_state)
    if isinstance(random_state, np.random.RandomState):
        return int(random_state.randint(0, 2**31 - 1))
    raise TypeError(f"bad random_state: {random_state!r}")


def zeros(shape, block_size=None, dtype=jnp.float32) -> Array:
    """All-zeros ds-array (reference: ds.zeros)."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = jax.device_put(jnp.zeros(pshape, dtype), _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


def full(shape, fill_value, block_size=None, dtype=jnp.float32) -> Array:
    """Constant-filled ds-array (reference: ds.full)."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = _full_op(pshape, tuple(int(s) for s in shape), float(fill_value), dtype)
    data = jax.device_put(data, _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


@partial(_pjit, static_argnames=("pshape", "shape", "dtype"), name="full")
def _full_op(pshape, shape, fill_value, dtype):
    return _zero_pad(jnp.full(pshape, fill_value, dtype), shape)


def ones(shape, block_size=None, dtype=jnp.float32) -> Array:
    """All-ones ds-array."""
    return full(shape, 1.0, block_size, dtype)


def identity(n, block_size=None, dtype=jnp.float32) -> Array:
    """n×n identity ds-array (reference: ds.identity)."""
    return eye(n, n, block_size, dtype)


def eye(n, m=None, block_size=None, dtype=jnp.float32) -> Array:
    """n×m eye ds-array (ones on the main diagonal; reference: ds.eye)."""
    m = n if m is None else m
    q = _mesh.pad_quantum()
    pshape = _padded_shape((n, m), q)
    data = jax.device_put(_eye_op(pshape, (int(n), int(m)), dtype), _mesh.data_sharding())
    return Array(data, (n, m), reg_shape=block_size)


@partial(_pjit, static_argnames=("pshape", "shape", "dtype"), name="eye")
def _eye_op(pshape, shape, dtype):
    r = lax.broadcasted_iota(jnp.int32, pshape, 0)
    c = lax.broadcasted_iota(jnp.int32, pshape, 1)
    return jnp.where((r == c) & (r < min(shape)), jnp.ones((), dtype), jnp.zeros((), dtype))


def rechunk(x: Array, new_blocks=None, mesh=None, *, schedule="auto",
            panels=None, overlap=None, nse=None) -> Array:
    """Reshard a ds-array to a new block-size hint and/or mesh layout —
    ON DEVICE, via a collective schedule, never a host materialization
    (round-11 rechunk PR; arXiv:2112.01075 discipline).

    Block size and mesh shape are deployment details, not API
    constraints: any estimator accepts any block size produced by any
    other stage, and this is the one primitive that moves a backing
    between pad quanta / mesh layouts when they DO differ.

    - ``new_blocks``: new block-size hint (metadata; ``None`` keeps the
      current hint).
    - ``mesh``: target :class:`jax.sharding.Mesh`; ``None`` = the library
      default mesh.
    - ``schedule``: ``"auto"`` | ``"xla"`` | ``"panels"`` | ``"dcn"`` |
      ``"deviceput"`` (see :mod:`dislib_tpu.ops.rechunk`;
      ``DSLIB_RECHUNK_SCHEDULE`` overrides auto).  Under auto, an
      already-canonical backing is a metadata-only no-op; a same-layout
      quantum change joins the dispatch-fusion graph (a mid-chain
      rechunk costs ZERO extra dispatches); a mesh-layout change over
      the same devices runs the explicit masked-psum panel exchange in
      ONE jitted program with peak in-flight bytes ≈ |array| / panels
      — on a MULTI-HOST device grid auto picks ``"dcn"``, the
      hierarchical variant that coalesces each host's contribution into
      at most ``hosts - 1`` inter-host messages per step (round-19
      DCN data-plane PR; ``dcn_accounting`` itemizes the traffic) —
      and a device-set change uses the runtime's device-to-device copy.
    - ``panels``: in-flight panel count for the collective schedule
      (default ``DSLIB_RECHUNK_PANELS`` = 4).
    - ``overlap``: the panel exchange's loop schedule — ``"db"``
      (double-buffered, the default: the next panel's broadcast is
      issued under the current panel's assemble) or ``"seq"``
      (sequential-phase); ``None`` reads ``DSLIB_OVERLAP``.  Bit-equal
      either way; the double buffer costs one extra in-flight panel
      (round-13 overlap PR — see the user guide's "Overlap &
      scheduling").

    - ``nse`` (sparse inputs only): target per-shard stored-entry pad —
      the sparse nse-quantum knob (``None`` keeps the minimum quantum
      multiple covering the densest target shard).

    SPARSE inputs (:class:`~dislib_tpu.data.sparse.SparseArray`) route
    through the SAME schedule names over the row-panel-sharded buffers
    (round-14 sparse PR): ``"xla"`` = fused nse re-pad on the same
    device grid, ``"panels"`` = one masked-psum panel exchange for a
    mesh-layout change, ``"deviceput"`` = gather + runtime copy for a
    device-set change — never the host, never a densification.

    The result re-satisfies the pad-and-mask invariant by construction:
    pad slices are exactly zero after the reshard, whatever the input
    tail carried."""
    from dislib_tpu.ops import rechunk as _rc
    from dislib_tpu.data.sparse import SparseArray
    if isinstance(x, SparseArray):
        if panels is not None:
            raise ValueError(
                "panels= applies to the DENSE panel exchange only — the "
                "sparse exchange broadcasts one panel per source "
                "row-rank (fixed); nse= is the sparse memory knob")
        out = x.resharded(mesh, schedule=schedule, nse=nse, overlap=overlap)
        if new_blocks is not None:
            out._reg_shape = _check_block_size(x._shape, new_blocks)
        return out
    if not isinstance(x, Array):
        raise TypeError(
            f"ds.rechunk needs a ds-array or SparseArray, "
            f"got {type(x).__name__}")
    reg = _check_block_size(x._shape, new_blocks) if new_blocks is not None \
        else x._reg_shape
    target = mesh if mesh is not None else _mesh.get_mesh()
    out_pshape = _padded_shape(x._shape, _mesh.pad_quantum(target))
    if target is _mesh.get_mesh() and schedule in ("auto", "xla") \
            and not _eager_mode():
        canonical = _mesh.data_sharding(target)
        if schedule == "auto":
            # already canonical: the block hint is pure metadata — share
            # the backing (concrete) or the pending expression (lazy;
            # chains are built for the current mesh by construction).
            # (An EXPLICIT schedule="xla" still emits the requantize
            # node — the user-reachable "re-assert the pad-and-mask
            # invariant" op, pinned by the poisoned-pad regressions.)
            if not x.is_lazy and tuple(x._concrete.shape) == out_pshape \
                    and x._concrete.sharding == canonical:
                return Array(x._concrete, x._shape, reg, x._sparse)
            if x.is_lazy and tuple(x._lazy.pshape) == out_pshape:
                return Array(x._lazy, x._shape, reg, x._sparse)
        if x.is_lazy or getattr(x._concrete, "sharding", None) == canonical:
            # same-layout quantum change: a fusion-graph node — the
            # reshard rides the chain and costs no dispatch of its own
            expr = _LazyExpr("rechunk",
                             (x._shape, tuple(out_pshape), _mesh_token()),
                             (x._node(),), out_pshape, x.dtype)
            return _lazy_array(expr, x._shape, reg, x._sparse)
    data, _sched = _rc.reshard(x._data, x._shape, target, schedule, panels,
                               overlap)
    return Array(data, x._shape, reg, x._sparse)


def ensure_canonical(x: Array) -> Array:
    """``x`` unchanged when its backing already matches the current
    mesh's pad quantum and layout; otherwise an on-device
    :func:`rechunk`.  The ingest guard for kernels with a hard layout
    requirement (shard_map row splits, SUMMA panels): estimators accept
    arrays built under ANY mesh and re-lay them out without a host hop."""
    pshape = _padded_shape(x._shape, _mesh.pad_quantum())
    if x.is_lazy:
        # a pending chain forces under the CURRENT mesh's constraints,
        # but its canvas shapes were fixed at build time — a chain built
        # before a quantum-changing mesh switch needs the fused
        # requantize node appended (review-found with a live repro:
        # old-quantum lazy operands crashed SUMMA's shard_map split)
        if tuple(x._lazy.pshape) == pshape:
            return x
        return rechunk(x)
    if tuple(x._concrete.shape) == pshape \
            and x._concrete.sharding == _mesh.data_sharding():
        return x
    return rechunk(x)


def _apply_axis_out_shape(out_spec, axis):
    """Logical 2-D result shape of an apply_along_axis (1-D maps get the
    reference's row/column-vector orientation)."""
    if out_spec.ndim == 1:
        return (1, int(out_spec.shape[0])) if axis == 0 \
            else (int(out_spec.shape[0]), 1)
    if out_spec.ndim == 2:
        return tuple(int(s) for s in out_spec.shape)
    raise ValueError(
        f"apply_along_axis: func produced a {out_spec.ndim}-D result; "
        "ds-arrays are 2-D")


def _apply_axis_kernel(cfg, xp):
    """``apply_along_axis`` as a fusion-node body (round-11 satellite):
    crop to the logical region, run the traced map, and place the result
    on its zero padded canvas — ONE dispatch riding whatever chain feeds
    it, instead of the old eager per-op path."""
    func, axis, in_shape, out_shape, out_pshape, fargs, fkwargs = cfg
    xv = xp[: in_shape[0], : in_shape[1]]
    out = jnp.apply_along_axis(func, axis, xv, *fargs, **dict(fkwargs))
    if out.ndim == 1:
        out = out.reshape(1, -1) if axis == 0 else out.reshape(-1, 1)
    canvas = jnp.zeros(out_pshape, out.dtype)
    return lax.dynamic_update_slice(canvas, out, (0, 0))


def apply_along_axis(func, axis, x: Array, *args, **kwargs) -> Array:
    """Apply ``func`` to 1-D slices of ``x`` along ``axis`` (reference:
    `dislib.data.array.apply_along_axis`, the generic user-level block map).

    Three tiers, fastest first (round-11 rechunk PR satellite):

    1. JAX-traceable ``func`` with hashable extra args: a fusion-graph
       node (:func:`fused_kernel`) — the whole map is ONE cached XLA
       dispatch (counter-pinned) and fuses into any surrounding op chain.
       Traceability is probed with ``jax.eval_shape`` (no execution, no
       transfer).
    2. Traceable but unhashable extras: the eager on-device
       ``jnp.apply_along_axis`` (still no host round trip).
    3. Not traceable at all: ``np.apply_along_axis`` on host — a
       device→host→device round trip that is orders of magnitude slower,
       so this tier WARNS with the original trace error."""
    logical_shape = x._shape
    spec = jax.ShapeDtypeStruct(logical_shape, x.dtype)
    try:
        out_spec = jax.eval_shape(
            lambda v: jnp.apply_along_axis(func, axis, v, *args, **kwargs),
            spec)
    except Exception as e:  # noqa: BLE001 — any trace failure → host tier
        import warnings
        warnings.warn(
            f"apply_along_axis: {getattr(func, '__name__', func)!r} is not "
            f"JAX-traceable ({type(e).__name__}: {e}); falling back to host "
            "NumPy (device->host->device round trip, far slower)",
            UserWarning, stacklevel=2)
        from dislib_tpu.utils.profiling import count_transfer
        logical = x._data[: x._shape[0], : x._shape[1]]
        count_transfer()
        out = np.apply_along_axis(
            func, axis, np.asarray(jax.device_get(logical)), *args, **kwargs)
        out = jnp.asarray(out)
        if out.ndim == 1:
            out = out.reshape(1, -1) if axis == 0 else out.reshape(-1, 1)
        return Array._from_logical(out, reg_shape=None)
    out_shape = _apply_axis_out_shape(out_spec, axis)
    cfg = (func, axis, logical_shape, out_shape,
           _padded_shape(out_shape, _mesh.pad_quantum()), tuple(args),
           tuple(sorted(kwargs.items())))
    try:
        stable = _stable_callable(func) and (hash(cfg) is not None)
    except TypeError:           # unhashable extras
        stable = False
    if not stable:
        # eager on-device tier: correct and host-free, but NOT entered
        # into the persistent fused-program cache — a fresh lambda per
        # call would pin a new executable forever (the fusion layer's
        # module-level-body contract; review-found)
        logical = x._data[: x._shape[0], : x._shape[1]]
        out = jnp.apply_along_axis(func, axis, logical, *args, **kwargs)
        if out.ndim == 1:
            out = out.reshape(1, -1) if axis == 0 else out.reshape(-1, 1)
        return Array._from_logical(out, reg_shape=None)
    return fused_kernel(_apply_axis_kernel, cfg, (x,), out_shape,
                        out_spec.dtype, out_pshape=cfg[4])


def _stable_callable(func) -> bool:
    """True when ``func`` is a module-level callable whose identity is
    stable across calls — the ``fused_kernel`` cache-key contract.  A
    per-call lambda/closure/partial gets a fresh identity every time and
    would grow the persistent executable cache without bound, so those
    route to the eager on-device tier instead."""
    import sys
    mod = getattr(func, "__module__", None)
    qual = getattr(func, "__qualname__", None)
    if not mod or not qual or "<" in qual:   # <lambda>, <locals>
        return False
    obj = sys.modules.get(mod)
    for part in qual.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return False
    return obj is func


def concat_rows(arrays) -> Array:
    """Stack ds-arrays vertically (logical concatenation)."""
    arrays = list(arrays)
    if not arrays:
        raise ValueError("concat_rows needs at least one array")
    cols = {a.shape[1] for a in arrays}
    if len(cols) > 1:
        raise ValueError(f"concat_rows: column counts differ: {sorted(cols)}")
    datas = [a._data[: a._shape[0], : a._shape[1]] for a in arrays]
    out = jnp.concatenate(datas, axis=0)
    return Array._from_logical(out, reg_shape=arrays[0]._reg_shape)


def concat_cols(arrays) -> Array:
    """Concatenate ds-arrays along columns (block-grid hstack role)."""
    arrays = list(arrays)
    if not arrays:
        raise ValueError("concat_cols needs at least one array")
    rows = {a.shape[0] for a in arrays}
    if len(rows) > 1:
        raise ValueError(f"concat_cols: row counts differ: {sorted(rows)}")
    datas = [a._data[: a._shape[0], : a._shape[1]] for a in arrays]
    out = jnp.concatenate(datas, axis=1)
    return Array._from_logical(out, reg_shape=arrays[0]._reg_shape)
