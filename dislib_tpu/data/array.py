"""The distributed array (ds-array) — dislib_tpu's single data structure.

Reference capability (SURVEY.md §3.1, `dislib/data/array.py :: class Array`):
a dense or sparse 2-D matrix partitioned into a grid of rectangular blocks,
each block a NumPy/CSR chunk held as a PyCOMPSs future; block-level ops are
``@task`` functions and nothing computes until an explicit sync
(``collect()`` / ``compss_wait_on``).

TPU-native redesign — NOT a block-of-futures translation:

- The whole matrix is ONE global :class:`jax.Array`, laid out on the library
  mesh with ``NamedSharding(P('rows', 'cols'))``.  Placement, inter-device
  movement and overlap come from XLA SPMD + async dispatch, which already
  plays the role the COMPSs task graph plays for the reference (SURVEY.md §8
  "Design stance").
- The reference's irregular top-left block / arbitrary ``block_size`` becomes
  *pad-and-mask metadata*: ``_data`` is padded so every dimension is a
  multiple of the mesh pad quantum, and the region outside the logical
  ``shape`` is ALWAYS ZERO.  That invariant makes contractions (matmul, sum,
  norm) correct with no masking, while min/max/mean mask or rescale
  explicitly.  Ops that could make padding non-zero re-zero it.
- ``block_size`` survives as a *hint* (`_reg_shape`) for API parity and for
  algorithms whose blocking is semantic (QR panels, tsQR tree arity); it no
  longer dictates physical layout — XLA tiles for the MXU itself.
- The "cheap to build, pay on sync" contract (SURVEY.md §4.6) is preserved by
  JAX's async dispatch: every method returns immediately with a live
  ``jax.Array``; ``collect()`` is the only host sync.

Sparse support: ``_sparse=True`` arrays keep a BCOO backing for memory-honest
storage where it pays (see `dislib_tpu/data/sparse.py`), with a dense+mask
fallback — the decision recorded per estimator as SURVEY §8 directs.
"""

from __future__ import annotations

import math
from functools import partial
from numbers import Number

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.parallel import mesh as _mesh

__all__ = [
    "Array",
    "array",
    "random_array",
    "zeros",
    "full",
    "ones",
    "identity",
    "eye",
    "apply_along_axis",
    "concat_rows",
    "concat_cols",
]


# ---------------------------------------------------------------------------
# padding helpers
# ---------------------------------------------------------------------------

def _padded_dim(n: int, quantum: int) -> int:
    return max(quantum, int(math.ceil(n / quantum)) * quantum)


def _padded_shape(shape, quantum):
    return tuple(_padded_dim(int(s), quantum) for s in shape)


def _pad_mask(padded_shape, logical_shape, dtype=jnp.bool_):
    """Boolean mask: True inside the logical region."""
    r = lax.broadcasted_iota(jnp.int32, padded_shape, 0) < logical_shape[0]
    c = lax.broadcasted_iota(jnp.int32, padded_shape, 1) < logical_shape[1]
    return (r & c).astype(dtype)


def _zero_pad(data, logical_shape):
    """Force the padding region to zero (the core Array invariant)."""
    if data.shape == tuple(logical_shape):
        return data
    return jnp.where(_pad_mask(data.shape, logical_shape), data, jnp.zeros((), data.dtype))


@partial(jax.jit, static_argnames=("padded_shape", "logical_shape"))
def _place(data, padded_shape, logical_shape):
    """Pad `data` (logical region) up to padded_shape with zeros."""
    out = jnp.zeros(padded_shape, data.dtype)
    out = lax.dynamic_update_slice(out, data.astype(out.dtype), (0, 0))
    del logical_shape
    return out


def _default_block_size(shape, mesh):
    r, c = _mesh.mesh_shape(mesh)
    return (max(1, -(-shape[0] // r)), max(1, -(-shape[1] // c)))


# ---------------------------------------------------------------------------
# the Array
# ---------------------------------------------------------------------------

class Array:
    """A 2-D matrix sharded over the device mesh.

    Parameters are internal; users build Arrays with :func:`array`,
    :func:`random_array`, the loaders in :mod:`dislib_tpu.data.io`, or as
    results of dislib_tpu operations.
    """

    def __init__(self, data: jax.Array, shape, reg_shape=None, sparse=False,
                 _skip_zero_check=True):
        self._data = data                       # padded, zero-outside-logical
        self._shape = (int(shape[0]), int(shape[1]))
        if reg_shape is None:
            reg_shape = _default_block_size(self._shape, None)
        self._reg_shape = (int(reg_shape[0]), int(reg_shape[1]))
        self._sparse = bool(sparse)

    # -- construction helpers ------------------------------------------------

    @classmethod
    def _from_logical(cls, data: jax.Array, reg_shape=None, sparse=False) -> "Array":
        """Wrap a logically-shaped (unpadded) device/host array."""
        shape = data.shape
        q = _mesh.pad_quantum()
        pshape = _padded_shape(shape, q)
        if tuple(shape) != pshape:
            data = _place(data, pshape, tuple(shape))
        data = jax.device_put(data, _mesh.data_sharding())
        return cls(data, shape, reg_shape=reg_shape, sparse=sparse)

    # -- metadata ------------------------------------------------------------

    @property
    def shape(self) -> tuple[int, int]:
        return self._shape

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def _n_blocks(self) -> tuple[int, int]:
        return (-(-self._shape[0] // self._reg_shape[0]),
                -(-self._shape[1] // self._reg_shape[1]))

    @property
    def block_size(self) -> tuple[int, int]:
        return self._reg_shape

    def __repr__(self):
        return (f"dslib.Array(shape={self._shape}, block_size={self._reg_shape}, "
                f"dtype={self.dtype}, sparse={self._sparse})")

    # -- sync points ---------------------------------------------------------

    def collect(self) -> np.ndarray:
        """Materialise on host — the analog of compss_wait_on + merge (SURVEY §4.6).

        Multi-host jobs: a row-sharded global array spans non-addressable
        devices, so the gather is a `process_allgather` over DCN (every
        host ends with the full logical array, the reference's
        gather-to-master contract)."""
        if not self._data.is_fully_addressable:
            from jax.experimental import multihost_utils
            out = np.asarray(multihost_utils.process_allgather(
                self._data, tiled=True))
        else:
            out = np.asarray(jax.device_get(self._data))
        out = out[: self._shape[0], : self._shape[1]]
        if self._sparse:
            import scipy.sparse as sp
            return sp.csr_matrix(out)
        return out

    def block_until_ready(self) -> "Array":
        self._data.block_until_ready()
        return self

    # -- layout --------------------------------------------------------------

    def rechunk(self, block_size) -> "Array":
        """Change the block-size hint.  Physical layout is mesh-determined, so
        this is metadata-only — the reference's data-movement rechunk
        (SURVEY §3.1) collapses to a no-op on a global jax.Array."""
        return Array(self._data, self._shape, reg_shape=block_size, sparse=self._sparse)

    def astype(self, dtype) -> "Array":
        return Array(self._data.astype(dtype), self._shape, self._reg_shape, self._sparse)

    def copy(self) -> "Array":
        return Array(self._data, self._shape, self._reg_shape, self._sparse)

    # -- transpose -----------------------------------------------------------

    def transpose(self) -> "Array":
        data = _transpose_op(self._data, self._shape)
        return Array._from_logical_padded(
            data, (self._shape[1], self._shape[0]),
            (self._reg_shape[1], self._reg_shape[0]), self._sparse)

    @property
    def T(self) -> "Array":
        return self.transpose()

    @classmethod
    def _from_logical_padded(cls, padded_data, shape, reg_shape=None, sparse=False):
        """Wrap data already padded+zeroed for `shape`."""
        padded_data = jax.device_put(padded_data, _mesh.data_sharding())
        return cls(padded_data, shape, reg_shape=reg_shape, sparse=sparse)

    # -- elementwise ---------------------------------------------------------

    def _coerce(self, other):
        if isinstance(other, Array):
            if other._shape != self._shape:
                # allow (1, n) / (n, 1) broadcasting
                if not _broadcastable(other._shape, self._shape):
                    raise ValueError(f"shape mismatch {self._shape} vs {other._shape}")
            return other
        if isinstance(other, Number):
            return other
        return NotImplemented

    def _ew(self, other, op):
        other = self._coerce(other)
        if other is NotImplemented:
            return NotImplemented
        if isinstance(other, Array):
            out_shape = _broadcast_shape(self._shape, other._shape)
            data = _ew_array_op(self._data, other._data, self._shape, other._shape,
                                out_shape, op)
            return Array(data, out_shape, self._reg_shape,
                         self._sparse and other._sparse)
        data = _ew_scalar_op(self._data, float(other) if not isinstance(other, bool) else other,
                             self._shape, op)
        # scalar mul/div/pow map zeros to zeros; add/sub of a nonzero
        # scalar destroys sparsity (the flag is metadata — data is dense)
        preserves = op in ("mul", "div", "pow") or float(other) == 0.0
        return Array(data, self._shape, self._reg_shape,
                     self._sparse and preserves)

    def __add__(self, o):  return self._ew(o, "add")
    def __radd__(self, o): return self._ew(o, "add")
    def __sub__(self, o):  return self._ew(o, "sub")
    def __rsub__(self, o): return self._ew(o, "rsub")
    def __mul__(self, o):  return self._ew(o, "mul")
    def __rmul__(self, o): return self._ew(o, "mul")
    def __truediv__(self, o):  return self._ew(o, "div")
    def __rtruediv__(self, o): return self._ew(o, "rdiv")
    def __pow__(self, o):  return self._ew(o, "pow")
    def __neg__(self):     return self._ew(-1.0, "mul")

    def __abs__(self):
        return Array(jnp.abs(self._data), self._shape, self._reg_shape, self._sparse)

    def sqrt(self) -> "Array":
        return Array(_zero_pad(jnp.sqrt(self._data), self._shape),
                     self._shape, self._reg_shape, self._sparse)

    def exp(self) -> "Array":
        return self._ew(0.0, "exp_")

    # -- matmul --------------------------------------------------------------

    def __matmul__(self, other):
        from dislib_tpu.math import matmul
        return matmul(self, other)

    # -- reductions ----------------------------------------------------------

    def _reduce(self, kind: str, axis=0):
        if axis not in (0, 1, None):
            raise ValueError("axis must be 0, 1 or None")
        data = _reduce_op(self._data, self._shape, kind, axis)
        if axis is None:
            shape = (1, 1)
        elif axis == 0:
            shape = (1, self._shape[1])
        else:
            shape = (self._shape[0], 1)
        return Array._from_logical_padded(_repad(data, shape), shape, None, False)

    def sum(self, axis=0):  return self._reduce("sum", axis)
    def mean(self, axis=0): return self._reduce("mean", axis)
    def min(self, axis=0):  return self._reduce("min", axis)
    def max(self, axis=0):  return self._reduce("max", axis)

    def norm(self, axis=0):
        return self._reduce("norm", axis)

    # -- indexing ------------------------------------------------------------

    def __getitem__(self, key):
        rows, cols = _split_key(key)
        r_idx, r_len = _normalize_index(rows, self._shape[0])
        c_idx, c_len = _normalize_index(cols, self._shape[1])
        data = _gather_op(self._data, r_idx, c_idx)
        new_shape = (r_len, c_len)
        return Array._from_logical_padded(_repad(data, new_shape), new_shape,
                                          None, self._sparse)

    # -- iteration over logical blocks (parity: Array._iterator) -------------

    def iterator(self, axis=0):
        """Yield row-block (axis=0) or col-block (axis=1) sub-arrays, one per
        `block_size` stripe — reference `Array._iterator` (SURVEY §3.1).

        Stripes are cheap contiguous slices of the padded backing (lax.slice
        + repad), not general gathers — each yield costs one slice op."""
        n = self._shape[axis]
        step = self._reg_shape[axis]
        m, c = self._shape
        for start in range(0, n, step):
            stop = min(start + step, n)
            if axis == 0:
                logical = self._data[start:stop, :c]
                shape = (stop - start, c)
            else:
                logical = self._data[:m, start:stop]
                shape = (m, stop - start)
            yield Array._from_logical_padded(_repad(logical, shape), shape,
                                             None, self._sparse)


def _broadcastable(a, b):
    return all(x == y or x == 1 or y == 1 for x, y in zip(a, b))


def _broadcast_shape(a, b):
    return tuple(max(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# jitted kernels (module-level so jit caches by shape)
# ---------------------------------------------------------------------------

_BINOPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "rsub": lambda a, b: b - a,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "rdiv": lambda a, b: b / a,
    "pow": lambda a, b: a ** b,
    "exp_": lambda a, b: jnp.exp(a),
}


@partial(jax.jit, static_argnames=("a_shape", "b_shape", "out_shape", "op"))
def _ew_array_op(a, b, a_shape, b_shape, out_shape, op):
    # crop each operand to its logical region, broadcast, then re-pad. The
    # crop/pad pair fuses to a masked op under XLA; it keeps broadcasting
    # semantics exact when a (1, n) operand's padded rows would otherwise
    # collide with the other operand's rows.
    av = a[: a_shape[0], : a_shape[1]]
    bv = b[: b_shape[0], : b_shape[1]]
    out = _BINOPS[op](av, bv)
    res = jnp.zeros(_padded_shape_like(a, b, out_shape), out.dtype)
    res = lax.dynamic_update_slice(res, out, (0, 0))
    return res


def _padded_shape_like(a, b, out_shape):
    # the padded canvas big enough for out_shape under the current quantum
    q_r = max(a.shape[0], b.shape[0])
    q_c = max(a.shape[1], b.shape[1])
    # out_shape is the broadcast of the logical shapes; the matching padded
    # canvas is the max of operand canvases in each dim.
    return (q_r, q_c)


@partial(jax.jit, static_argnames=("shape", "op"))
def _ew_scalar_op(a, scalar, shape, op):
    out = _BINOPS[op](a, jnp.asarray(scalar, a.dtype))
    return _zero_pad(out, shape)


@partial(jax.jit, static_argnames=("shape",))
def _transpose_op(a, shape):
    return a.T


@partial(jax.jit, static_argnames=("shape", "kind", "axis"))
def _reduce_op(a, shape, kind, axis):
    mask = _pad_mask(a.shape, shape)
    if kind in ("sum", "norm", "mean"):
        x = jnp.where(mask, a, 0)
        if kind == "norm":
            x = x * x
        red = jnp.sum(x, axis=axis, keepdims=True) if axis is not None else \
            jnp.sum(x, keepdims=True).reshape(1, 1)
        if kind == "mean":
            n = shape[axis] if axis is not None else shape[0] * shape[1]
            red = red / n
        if kind == "norm":
            red = jnp.sqrt(red)
    else:
        fill = jnp.asarray(jnp.inf if kind == "min" else -jnp.inf, a.dtype)
        x = jnp.where(mask, a, fill)
        fn = jnp.min if kind == "min" else jnp.max
        red = fn(x, axis=axis, keepdims=True) if axis is not None else \
            fn(x, keepdims=True).reshape(1, 1)
    return red


def _repad(logical_data, shape):
    """Pad logical(-region) data out to the current quantum and zero-fill."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    cropped = logical_data[: shape[0], : shape[1]]
    if cropped.shape == pshape:
        return jax.device_put(cropped, _mesh.data_sharding())
    out = _place(cropped, pshape, shape)
    return jax.device_put(out, _mesh.data_sharding())


def _gather_op(a, r_idx, c_idx):
    if isinstance(r_idx, slice) and isinstance(c_idx, slice):
        return a[r_idx, c_idx]
    if isinstance(r_idx, slice):
        return a[r_idx, :][:, c_idx]
    if isinstance(c_idx, slice):
        return a[r_idx, :][:, c_idx]
    return a[r_idx, :][:, c_idx]


def _split_key(key):
    if isinstance(key, tuple):
        if len(key) != 2:
            raise IndexError("ds-arrays are 2-D: index with at most two axes")
        return key
    return key, slice(None)


def _normalize_index(idx, dim):
    """Return (index object over the padded array, result length)."""
    if isinstance(idx, (int, np.integer)):
        i = int(idx)
        if i < 0:
            i += dim
        if not 0 <= i < dim:
            raise IndexError(f"index {idx} out of bounds for dim {dim}")
        return slice(i, i + 1), 1
    if isinstance(idx, slice):
        start, stop, step = idx.indices(dim)
        if step <= 0:
            raise IndexError("negative slice steps not supported")
        length = max(0, -(-(stop - start) // step))
        return slice(start, stop, step), length
    # fancy indexing with a list / ndarray of ints (or bools)
    arr = np.asarray(idx)
    if arr.dtype == bool:
        if arr.shape[0] != dim:
            raise IndexError("boolean index length mismatch")
        arr = np.nonzero(arr)[0]
    if arr.size and not np.issubdtype(arr.dtype, np.integer):
        # silent float→int truncation would index the wrong rows; an empty
        # selection (np.asarray([]) is float64) stays valid, as in NumPy
        raise IndexError(f"fancy index must be integer or boolean, got "
                         f"dtype {arr.dtype}")
    arr = arr.astype(np.int64)
    arr = np.where(arr < 0, arr + dim, arr)
    if arr.size and (arr.min() < 0 or arr.max() >= dim):
        raise IndexError("fancy index out of bounds")
    return arr, int(arr.shape[0])


# ---------------------------------------------------------------------------
# public constructors  (parity: dislib.data.array constructors, SURVEY §3.1)
# ---------------------------------------------------------------------------

def array(x, block_size=None, dtype=None) -> Array:
    """Build a ds-array from host data (ndarray, nested lists, or scipy sparse).

    ``dtype=None`` keeps the TPU-native float32 default but WARNS once when
    that silently narrows float64 input (the reference's blocks are NumPy
    float64 — a port should not change precision silently).  Pass an
    explicit ``dtype=`` to silence the warning; ``dtype=np.float64`` is
    honoured when JAX x64 mode is enabled (CPU rig) and raises a clear
    error otherwise."""
    import scipy.sparse as sp
    sparse = sp.issparse(x)
    if sparse:
        x = x.toarray()
    on_device = isinstance(x, jax.Array)
    if not on_device:
        x = np.asarray(x)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if x.ndim != 2:
        raise ValueError("ds-arrays are 2-dimensional")
    if on_device:
        # device input: same dtype policy, applied without a host round-trip
        x = _coerce_dtype(x, dtype)
    else:
        x = jnp.asarray(_coerce_dtype(x, dtype))
    if block_size is None:
        block_size = _default_block_size(x.shape, None)
    block_size = _check_block_size(x.shape, block_size)
    return Array._from_logical(x, reg_shape=block_size, sparse=sparse)


def _require_dtype_support(dtype):
    """Reject dtypes the backend would silently narrow (f64 without x64)."""
    if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
        raise ValueError(
            "dtype=float64 requires JAX x64 mode (JAX_ENABLE_X64=1 or "
            "jax.config.update('jax_enable_x64', True)); the TPU-native "
            "default is float32")


def _coerce_dtype(x, dtype):
    """Apply the library dtype policy (see :func:`array`) — the ONE
    implementation, shared by the host (ndarray) and device (jax.Array)
    input paths."""
    if dtype is not None:
        _require_dtype_support(dtype)
        dtype = np.dtype(dtype)
        return x if x.dtype == dtype else x.astype(dtype)
    if x.dtype == np.float64:
        _warn_f64_narrowing()
        return x.astype(np.float32)
    return x


def _warn_f64_narrowing():
    import warnings
    warnings.warn(
        "ds.array received float64 data and is narrowing it to float32 "
        "(the TPU-native default). Pass dtype=np.float32 to silence, or "
        "dtype=np.float64 with JAX x64 mode to keep full precision.",
        UserWarning, stacklevel=4)


def _check_block_size(shape, block_size):
    """Validate and return the effective block size: oversized blocks clamp
    to the logical shape (physical layout is mesh-determined anyway — the
    block size only drives `iterator` stripes and `_reg_shape` metadata)."""
    br, bc = block_size
    if br <= 0 or bc <= 0:
        raise ValueError("block_size entries must be positive")
    return (min(br, shape[0]) if shape[0] > 0 else br,
            min(bc, shape[1]) if shape[1] > 0 else bc)


def random_array(shape, block_size=None, random_state=None,
                 dtype=jnp.float32) -> Array:
    """Uniform [0, 1) ds-array; deterministic per seed, seeded per the whole
    array (the reference seeds per block — an implementation artifact of
    task-parallel generation, not an API contract)."""
    _require_dtype_support(dtype)
    seed = _seed_from(random_state)
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = _random_uniform(jax.random.PRNGKey(seed), pshape,
                           tuple(int(s) for s in shape), np.dtype(dtype).name)
    data = jax.device_put(data, _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


@partial(jax.jit, static_argnames=("pshape", "shape", "dtype"))
def _random_uniform(key, pshape, shape, dtype):
    vals = jax.random.uniform(key, pshape, dtype=dtype)
    return _zero_pad(vals, shape)


def _seed_from(random_state):
    if random_state is None:
        return np.random.randint(0, 2**31 - 1)
    if isinstance(random_state, (int, np.integer)):
        return int(random_state)
    if isinstance(random_state, np.random.RandomState):
        return int(random_state.randint(0, 2**31 - 1))
    raise TypeError(f"bad random_state: {random_state!r}")


def zeros(shape, block_size=None, dtype=jnp.float32) -> Array:
    """All-zeros ds-array (reference: ds.zeros)."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = jax.device_put(jnp.zeros(pshape, dtype), _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


def full(shape, fill_value, block_size=None, dtype=jnp.float32) -> Array:
    """Constant-filled ds-array (reference: ds.full)."""
    q = _mesh.pad_quantum()
    pshape = _padded_shape(shape, q)
    data = _full_op(pshape, tuple(int(s) for s in shape), float(fill_value), dtype)
    data = jax.device_put(data, _mesh.data_sharding())
    return Array(data, shape, reg_shape=block_size)


@partial(jax.jit, static_argnames=("pshape", "shape", "dtype"))
def _full_op(pshape, shape, fill_value, dtype):
    return _zero_pad(jnp.full(pshape, fill_value, dtype), shape)


def ones(shape, block_size=None, dtype=jnp.float32) -> Array:
    """All-ones ds-array."""
    return full(shape, 1.0, block_size, dtype)


def identity(n, block_size=None, dtype=jnp.float32) -> Array:
    """n×n identity ds-array (reference: ds.identity)."""
    return eye(n, n, block_size, dtype)


def eye(n, m=None, block_size=None, dtype=jnp.float32) -> Array:
    """n×m eye ds-array (ones on the main diagonal; reference: ds.eye)."""
    m = n if m is None else m
    q = _mesh.pad_quantum()
    pshape = _padded_shape((n, m), q)
    data = jax.device_put(_eye_op(pshape, (int(n), int(m)), dtype), _mesh.data_sharding())
    return Array(data, (n, m), reg_shape=block_size)


@partial(jax.jit, static_argnames=("pshape", "shape", "dtype"))
def _eye_op(pshape, shape, dtype):
    r = lax.broadcasted_iota(jnp.int32, pshape, 0)
    c = lax.broadcasted_iota(jnp.int32, pshape, 1)
    return jnp.where((r == c) & (r < min(shape)), jnp.ones((), dtype), jnp.zeros((), dtype))


def apply_along_axis(func, axis, x: Array, *args, **kwargs) -> Array:
    """Apply ``func`` to 1-D slices of ``x`` along ``axis`` (reference:
    `dislib.data.array.apply_along_axis`, the generic user-level block map).

    ``func`` is first attempted as a JAX-traceable function (vmapped on
    device, so the map runs sharded); if tracing fails it falls back to
    ``np.apply_along_axis`` on host — a device→host→device round trip that
    is orders of magnitude slower, so the fallback WARNS with the original
    trace error."""
    logical = x._data[: x._shape[0], : x._shape[1]]
    try:
        out = jnp.apply_along_axis(func, axis, logical, *args, **kwargs)
    except Exception as e:  # noqa: BLE001 — any trace failure falls back
        import warnings
        warnings.warn(
            f"apply_along_axis: {getattr(func, '__name__', func)!r} is not "
            f"JAX-traceable ({type(e).__name__}: {e}); falling back to host "
            "NumPy (device->host->device round trip, far slower)",
            UserWarning, stacklevel=2)
        out = np.apply_along_axis(func, axis, np.asarray(jax.device_get(logical)),
                                  *args, **kwargs)
        out = jnp.asarray(out)
    if out.ndim == 1:
        out = out.reshape(1, -1) if axis == 0 else out.reshape(-1, 1)
    return Array._from_logical(out, reg_shape=None)


def concat_rows(arrays) -> Array:
    """Stack ds-arrays vertically (logical concatenation)."""
    arrays = list(arrays)
    if not arrays:
        raise ValueError("concat_rows needs at least one array")
    cols = {a.shape[1] for a in arrays}
    if len(cols) > 1:
        raise ValueError(f"concat_rows: column counts differ: {sorted(cols)}")
    datas = [a._data[: a._shape[0], : a._shape[1]] for a in arrays]
    out = jnp.concatenate(datas, axis=0)
    return Array._from_logical(out, reg_shape=arrays[0]._reg_shape)


def concat_cols(arrays) -> Array:
    """Concatenate ds-arrays along columns (block-grid hstack role)."""
    arrays = list(arrays)
    if not arrays:
        raise ValueError("concat_cols needs at least one array")
    rows = {a.shape[0] for a in arrays}
    if len(rows) > 1:
        raise ValueError(f"concat_cols: row counts differ: {sorted(rows)}")
    datas = [a._data[: a._shape[0], : a._shape[1]] for a in arrays]
    out = jnp.concatenate(datas, axis=1)
    return Array._from_logical(out, reg_shape=arrays[0]._reg_shape)
