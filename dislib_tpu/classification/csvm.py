"""Cascade SVM (reference: `dislib/classification/csvm` — per-partition
sklearn `SVC` fit tasks, pairwise merge of support vectors up an arity tree,
global SVs fed back for the next global iteration, convergence via the dual
Lagrangian objective; SURVEY.md §3.3).

TPU-native redesign — no sklearn, no ragged SV sets:

- The local solver is an **in-JAX dual SVM**: maximize
  ``W(α) = Σα − ½ αᵀQα`` s.t. ``0 ≤ α ≤ C`` with ``Q = (K + 1) ∘ yyᵀ``.
  The bias is absorbed by the K+1 kernel augmentation (equivalent to a
  penalized intercept / constant feature), which removes the equality
  constraint ``Σyα = 0`` — that constraint is what makes SMO sequential and
  scalar, i.e. hostile to the MXU.  What remains is box-constrained
  projected gradient ascent: ``α ← clip(α + η(1 − Qα), 0, C)`` — one GEMV
  per step inside a `lax.while_loop`, step size from the Gershgorin bound
  ``η = 1/max_row_sum(|Q|)``.  ``DSLIB_CSVM_SOLVER=fista`` switches to
  accelerated PG with adaptive restart (same fixed point + stopping
  rule, fewer sequential steps — the cascade's TPU latency driver; the
  bench row A/Bs both, see `_use_fista`).
- The reference's *growing* SV sets become **fixed-capacity index buffers
  with masking** (SURVEY §8 "hard parts" #1): a cascade node is a padded
  vector of sample indices; padded slots get ``C = 0`` so their α is pinned
  at 0 and they can never become SVs.  Each cascade level is ONE `vmap`-ed
  solve over all nodes of the level (the reference's task-level parallelism,
  recovered as batching).
- **Sparse-native** (SURVEY §8 hard part 2): a `SparseArray` fit keeps a
  host CSR copy (O(nnz) — the layout the reference's per-partition SVC
  tasks consume on CPU workers) and stages each node batch's sub-Gram
  with one sparse GEMM; the dual solves run on device from the
  precomputed K, and sparse queries classify via one spmm cross-term.
  The full matrix is never densified on either side of the fit.
- Kernel values are computed **per node** from gathered rows — a node's
  (cap, cap) sub-Gram, never the m×m Gram of the whole fit set.  Level-0
  partition height is capped (``DSLIB_CSVM_MAX_PARTITION``, default 4096)
  so an inherited default block size of m/p cannot make level 0 quadratic
  in m, and wide levels solve in node batches bounded by a byte budget
  (``DSLIB_CSVM_SOLVE_BUDGET``, default 2 GiB) — peak memory per level is
  O(batch·cap²) regardless of m, which is what lets the cascade scale
  past single-chip HBM the way the reference's partitioning does.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad, ensure_canonical, \
    fused_kernel
from dislib_tpu.ops import distances_sq
from dislib_tpu.ops.base import precise
from dislib_tpu.utils.profiling import profiled_jit as _pjit
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop


class CascadeSVM(BaseEstimator):
    """Binary SVM trained by cascades of partial solves.

    Parameters (reference parity)
    ----------
    cascade_arity : int, default 2 — fan-in of the SV merge tree.
    max_iter : int, default 5 — global cascade iterations.
    tol : float, default 1e-3 — relative change of the dual objective.
    kernel : 'rbf' or 'linear'.
    c : float, default 1.0 — box constraint.
    gamma : 'auto' or float — rbf width; 'auto' = 1/n_features.
    check_convergence : bool, default True.
    random_state : unused (fit is deterministic); kept for parity.

    Attributes
    ----------
    classes_ : ndarray (2,) — original labels, index = predicted class.
    converged_ : bool
    iterations_n : int (alias n_iter_)
    support_vectors_count_ : int
    """

    _private_fitted_attrs = ("_sv_x", "_sv_y", "_sv_alpha", "_sv_idx",
                             "_gamma_fit")

    def __init__(self, cascade_arity=2, max_iter=5, tol=1e-3, kernel="rbf",
                 c=1.0, gamma="auto", check_convergence=True, random_state=None,
                 verbose=False):
        self.cascade_arity = cascade_arity
        self.max_iter = max_iter
        self.tol = tol
        self.kernel = kernel
        self.c = c
        self.gamma = gamma
        self.check_convergence = check_convergence
        self.random_state = random_state
        self.verbose = verbose

    # -- fitting -------------------------------------------------------------

    def _gamma_value(self, n_features):
        if self.gamma == "auto":
            return 1.0 / n_features
        return float(self.gamma)

    def fit(self, x: Array, y: Array, checkpoint=None, health=None):
        """Fit the cascade.  With ``checkpoint=FitCheckpoint(path, every=k)``
        the global-iteration state (SV indices/alphas, objective, counter)
        snapshots every k iterations; a re-run resumes from the snapshot and
        lands on the uninterrupted run's model (each global iteration
        depends only on the fed-back SV set and previous objective — SURVEY
        §6 checkpoint/resume).

        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`.
        The cascade's per-iteration state (top-node alphas, dual
        objective) is host-side already, so the guard checks it directly
        (`check_host`) at each global iteration — no extra dispatches; a
        tripped guard rolls back to the last-good snapshot or raises a
        typed ``NumericalDivergence``."""
        if self.kernel not in ("rbf", "linear"):
            raise ValueError(f"unsupported kernel {self.kernel!r}")
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        m, n = x.shape
        y_host = np.asarray(y.collect()).ravel()
        classes = np.unique(y_host)
        if len(classes) != 2:
            raise ValueError("CascadeSVM is a binary classifier; got "
                             f"{len(classes)} classes")
        self.classes_ = classes
        y_pm = np.where(y_host == classes[1], 1.0, -1.0).astype(np.float32)

        gamma = self._gamma_value(n)
        # resolved ONCE per fit and threaded as a trace-time static (the
        # _use_cholqr pattern: flipping the env var retraces, never
        # silently ignored)
        solver = "fista" if _use_fista() else "pg"
        # SPARSE-NATIVE path (SURVEY §8 hard part 2): the matrix is never
        # densified.  A host CSR copy (O(nnz), the same layout the
        # reference's per-partition SVC tasks consume on CPU workers)
        # stages each node batch's sub-Gram; the boxed-dual solves stay on
        # device.  Dense inputs keep the all-device gather path.
        from dislib_tpu.data.sparse import SparseArray
        sparse_in = isinstance(x, SparseArray)
        ell = x_csr = k_of = None
        if sparse_in:
            # preferred staging: device-resident ELL row gather — each node
            # batch densifies its rows and computes its sub-Gram ON DEVICE,
            # no host scipy product in the cascade loop (round-3 verdict
            # #5).  Falls back to host-CSR staging when row-nnz skew makes
            # the padded ELL buffers bigger than the budget.
            ell = x.ell()
            if ell is not None:
                xv = None
                yv = jnp.asarray(y_pm)
            else:
                x_csr = x.collect().tocsr()
                rowsq = np.asarray(x_csr.multiply(x_csr).sum(axis=1),
                                   dtype=np.float32).ravel()
                k_of = _host_gram(x_csr, rowsq, self.kernel, gamma)
                xv = yv = None
        else:
            xv = x._data
            yv = jnp.asarray(np.pad(y_pm, (0, xv.shape[0] - m)))

        # level-0 partitions = row-block index chunks (reference: one SVC
        # task per row block) — BOUNDED: a partition of p rows costs a
        # (p, p) sub-Gram, so inheriting a huge default block size (m/p_mesh)
        # would make level 0 quadratic in m.  The cascade exists precisely
        # to keep solves small; cap at DSLIB_CSVM_MAX_PARTITION (4096).
        part = min(max(1, x._reg_shape[0]), _max_partition())
        nodes0 = _pack_nodes([np.arange(s, min(s + part, m))
                              for s in range(0, m, part)])

        box = {"sv_idx": None, "last_w": None, "x": x,
               "xv": xv, "yv": yv, "ell": ell}

        def rebind(mesh):
            # elastic re-staging (round 16): the cascade's node solves
            # read the staged rows, so a mesh change re-stages them —
            # dense re-canonicalizes x and re-pads y to the new quantum,
            # the sparse ELL layout re-lands its backing (the host-CSR
            # fallback and `k_of` are mesh-independent and stay put)
            if sparse_in:
                if mesh is not None:
                    box["x"].sharded(mesh)
                    if x_csr is None:
                        box["ell"] = box["x"].ell()
                return
            from dislib_tpu.data.array import ensure_canonical
            xb = box["x"]
            box["x"] = xb.force() if mesh is None else ensure_canonical(xb)
            if mesh is not None:
                xv2 = box["x"]._data
                box["xv"] = xv2
                box["yv"] = jnp.asarray(
                    np.pad(y_pm, (0, xv2.shape[0] - m)))
        self.converged_ = False
        fp = digest = None
        if checkpoint is not None:
            # fingerprint of everything the fed-back SV state depends on —
            # exact part: shape, hyperparameters, level-0 partitioning;
            # tolerant part: data digests (plain AND index-weighted sums of
            # x and y, so a row permutation changes them) compared with a
            # small relative tolerance, because float reductions differ in
            # the last ulps across mesh topologies and a legitimate
            # resume-after-preemption may land on different hardware.  A
            # sum digest is best-effort: a tiny relative perturbation at
            # very large m can evade it.  NaN digests never match (NaN
            # data fails closed — refuse the resume).  The x digests are
            # einsum reductions (no m×n temporary); pad rows are zero, so
            # padded sums equal logical sums.  Computed only for
            # checkpointed fits.
            fp = np.asarray([m, n, float(gamma), float(self.c),
                             float(self.cascade_arity),
                             float(("rbf", "linear").index(self.kernel)),
                             float(part)], np.float64)
            if sparse_in:
                # same math as the dense einsum digests, over the nonzeros
                # (Σv and Σ row·v) — works for both staging modes
                idxh = np.asarray(jax.device_get(x._bcoo.indices))
                valh = np.asarray(jax.device_get(x._bcoo.data), np.float64)
                x_sum = float(valh.sum())
                x_rowsum = float((valh * idxh[:, 0]).sum())
            else:
                # shared split-iota reduction: exact index weights past
                # 2^24 rows (a plain f32 iota collides adjacent indices)
                from dislib_tpu.utils.checkpoint import digest_sums
                x_sum, x_rowsum = digest_sums(xv)
            from dislib_tpu.utils.checkpoint import versioned_digest, \
                validate_snapshot
            digest = versioned_digest(
                x_sum, x_rowsum, float(y_pm.sum()),
                float(y_pm @ np.arange(m, dtype=np.float64)))
        loop = _fitloop.ChunkedFitLoop(
            "csvm", checkpoint=checkpoint, health=health,
            max_iter=self.max_iter, chunk_iters=1,
            save_every=checkpoint.every if checkpoint is not None else 1,
            elastic=rebind)

        def init(rem):
            box.update(sv_idx=None, sv_alpha=None, last_w=None)
            return _fitloop.LoopState(())   # state is host-side

        def restore(snap, rem):
            validate_snapshot(snap, fp, digest)
            box["sv_idx"] = np.asarray(snap["sv_idx"], np.int64)
            box["sv_alpha"] = np.asarray(snap["sv_alpha"], np.float32)
            box["last_w"] = float(snap["last_w"])
            # a converged snapshot only short-circuits when THIS fit also
            # checks convergence — resuming with check_convergence=False
            # means "run the iterations"
            return _fitloop.LoopState((), it=int(snap["n_iter"]),
                                      done=bool(snap["converged"])
                                      and self.check_convergence)

        def step(st, chunk):
            it = st.it + 1
            if box["sv_idx"] is not None and len(box["sv_idx"]):
                # feed global SVs back into every level-0 partition
                # (dedupe: a partition may already own some of them)
                rows = [np.unique(np.r_[nodes0[i][nodes0[i] >= 0],
                                        box["sv_idx"]])
                        for i in range(nodes0.shape[0])]
                nodes = _pack_nodes(rows)
            else:
                nodes = nodes0
            # cascade reduction to one node
            while True:
                alphas, objs = _solve_level_batched(box["xv"], box["yv"],
                                                    nodes,
                                                    float(self.c), n,
                                                    self.kernel, gamma,
                                                    k_of=k_of, y_host=y_pm,
                                                    ell=box["ell"],
                                                    solver=solver)
                if nodes.shape[0] == 1:
                    break
                nodes = self._merge_level(nodes, np.asarray(alphas))
            # top node: global SVs + dual objective
            top_idx, top_alpha = nodes[0], np.asarray(alphas[0])

            def commit():
                # deferred behind the verdict: a faulted iteration (or the
                # typed raise with no rollback budget left) must never
                # leave its values in the box/attrs — a refit that raises
                # keeps the previously fitted model usable
                keep = (top_alpha > 1e-8) & (top_idx >= 0)
                if not keep.any():
                    # degenerate solve (tiny C / degenerate data): an
                    # empty SV set would make decision_function
                    # identically 0 — keep the max-α sample so the model
                    # stays usable, and say so
                    import warnings
                    warnings.warn("CascadeSVM: no support vector exceeded "
                                  "alpha=1e-8; retaining the max-alpha "
                                  "sample", RuntimeWarning, stacklevel=2)
                    keep[:] = False
                    keep[int(np.argmax(np.where(top_idx >= 0, top_alpha,
                                                -np.inf)))] = True
                w = float(objs[0])   # top node's dual objective (same solve)
                done = bool(self.check_convergence
                            and box["last_w"] is not None
                            and abs(w - box["last_w"])
                            <= self.tol * max(abs(w), 1e-12))
                box.update(sv_idx=top_idx[keep], last_w=w,
                           sv_alpha=top_alpha[keep].astype(np.float32))
                from dislib_tpu.utils.dlog import verbose_logger
                verbose_logger("csvm", self.verbose).info(
                    "iter %d: W=%.6f, SVs=%d", it, w, len(box["sv_idx"]))
                return _fitloop.LoopState((), it, done)

            return _fitloop.ChunkOutcome(
                commit, host_values={"sv_alpha": top_alpha,
                                     "objective": np.asarray(objs[0])})

        def snapshot(st):
            # host-side state already — the async offload moves the
            # checksum+atomic write off the cascade's critical path
            return {"sv_idx": np.asarray(box["sv_idx"], np.int64),
                    "sv_alpha": box["sv_alpha"],
                    "last_w": box["last_w"], "n_iter": st.it, "fp": fp,
                    "digest": digest, "converged": st.done}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        self.iterations_n = self.n_iter_ = st.it
        self.converged_ = st.done
        self._sv_alpha = box["sv_alpha"]
        self.fit_info_ = loop.info
        sv_idx = box["sv_idx"]
        self._sv_idx = sv_idx
        # gather SV rows only (n_sv × n, never the dataset): from the host
        # CSR on the sparse path, on device for dense inputs
        if sparse_in:
            if box["ell"] is not None:
                self._sv_x = _fetch(_ell_rows_dense(
                    box["ell"][0], box["ell"][1], jnp.asarray(sv_idx), n))
            else:
                self._sv_x = np.asarray(x_csr[sv_idx].toarray(), np.float32)
        else:
            self._sv_x = _fetch(box["x"]._data[jnp.asarray(sv_idx), : n])
        self._sv_y = y_pm[sv_idx]
        self._gamma_fit = gamma
        self.support_vectors_count_ = len(sv_idx)
        return self

    def _merge_level(self, nodes, alphas):
        """Group nodes by cascade_arity; each group's (deduped) SV indices
        form one next-level node."""
        a = self.cascade_arity
        groups = [list(range(i, min(i + a, nodes.shape[0])))
                  for i in range(0, nodes.shape[0], a)]
        rows = []
        for g in groups:
            sv = []
            for ni in g:
                keep = (alphas[ni] > 1e-8) & (nodes[ni] >= 0)
                sv.extend(nodes[ni][keep].tolist())
            sv = np.unique(sv) if sv else \
                np.asarray([int(nodes[g[0]][0])])  # never emit an empty node
            rows.append(sv)
        return _pack_nodes(rows)

    # -- inference -----------------------------------------------------------

    def decision_function(self, x: Array) -> Array:
        """Signed margin per row.  Dense queries build a fusion-graph node
        (one cached dispatch end-to-end for a scaler → decision chain);
        sparse queries stay an eager spmm kernel."""
        self._check_fitted()
        from dislib_tpu.data.sparse import SparseArray
        if isinstance(x, SparseArray):
            # sparse queries: cross-term as one spmm against the (small)
            # dense SV block — the query matrix never densifies
            dec = _decision_sparse(x._bcoo, x.row_norms_sq(),
                                   jnp.asarray(self._sv_x),
                                   jnp.asarray(self._sv_y),
                                   jnp.asarray(self._sv_alpha),
                                   self.kernel, self._gamma_fit)
            return Array._from_logical_padded(_repad(dec, (x.shape[0], 1)),
                                              (x.shape[0], 1))
        # serve on the CURRENT mesh: an input built before an elastic
        # resize re-lands on device (never the host) — round 16
        x = ensure_canonical(x)
        sv_x, sv_y, sv_alpha, gamma = self._predict_leaves(
            self._sv_x, self._sv_y, self._sv_alpha, self._gamma_leaf())
        return fused_kernel(
            _decision_kernel, (x.shape, self.kernel),
            (x, sv_x, sv_y, sv_alpha, gamma),
            (x.shape[0], 1), jnp.float32, out_pshape=(x._pshape[0], 1))

    def predict(self, x: Array) -> Array:
        """Class label per row.  The dense path is one fusion node —
        decision values, thresholding, AND the class-value lookup all run
        on device (the old host round-trip between decision and label
        selection was a hidden per-predict sync, caught by the round-9
        `dispatches_per_predict` counters)."""
        self._check_fitted()
        from dislib_tpu.data.sparse import SparseArray
        if isinstance(x, SparseArray):
            dec = self.decision_function(x).collect().ravel()
            labels = self.classes_[(dec > 0).astype(np.int64)]
            dt = np.int32 if np.issubdtype(labels.dtype, np.integer) \
                else np.float32
            out = jnp.asarray(labels.astype(dt)[:, None])
            return Array._from_logical_padded(_repad(out, (x.shape[0], 1)),
                                              (x.shape[0], 1))
        x = ensure_canonical(x)     # serve on the CURRENT mesh (round 16)
        sv_x, sv_y, sv_alpha, gamma, classes = self._predict_leaves(
            self._sv_x, self._sv_y, self._sv_alpha, self._gamma_leaf(),
            self._classes_leaf())
        return fused_kernel(
            _csvm_predict_kernel, (x.shape, self.kernel),
            (x, sv_x, sv_y, sv_alpha, gamma, classes),
            (x.shape[0], 1), classes.dtype, out_pshape=(x._pshape[0], 1))

    def score(self, x: Array, y: Array) -> float:
        pred = self.predict(x).collect().ravel()
        truth = np.asarray(y.collect()).ravel()
        return float(np.mean(pred == truth))

    def _gamma_leaf(self):
        """``gamma`` as a host scalar array with stable identity, so the
        `_predict_leaves` device cache hits on repeat predict calls (gamma
        stays a DYNAMIC operand — one compiled decision program serves
        every gamma, as the pre-fusion jitted kernel did)."""
        cached = getattr(self, "_gamma_cache", None)
        if cached is None or cached[0] != self._gamma_fit:
            self._gamma_cache = (self._gamma_fit,
                                 np.float32(self._gamma_fit))
        return self._gamma_cache[1]

    def _check_fitted(self):
        if not hasattr(self, "_sv_x"):
            raise RuntimeError("CascadeSVM is not fitted")


def _max_partition() -> int:
    return int(os.environ.get("DSLIB_CSVM_MAX_PARTITION", 4096))


def _solve_budget() -> int:
    return int(os.environ.get("DSLIB_CSVM_SOLVE_BUDGET", 2 << 30))


def _host_gram(csr, rowsq, kernel, gamma):
    """Sub-Gram stager for the sparse path: per node, slice the node's rows
    out of the host CSR (the reference's per-partition data movement) and
    compute its (cap, cap) kernel block with one sparse GEMM — the full
    matrix is never densified; the dense footprint is the sub-Gram the
    dual solve needs anyway.  Padded node slots stay zero rows (their C is
    pinned to 0 in the solve)."""
    def k_of(nodes_chunk):
        w, cap = nodes_chunk.shape
        k = np.zeros((w, cap, cap), np.float32)
        for t in range(w):
            idx = nodes_chunk[t][nodes_chunk[t] >= 0]
            if not len(idx):
                continue
            sub = csr[idx]
            cross = np.asarray((sub @ sub.T).todense(), dtype=np.float32)
            if kernel == "rbf":
                rq = rowsq[idx]
                cross = np.exp(-gamma * np.maximum(
                    rq[:, None] + rq[None, :] - 2.0 * cross, 0.0))
            nv = len(idx)
            k[t, :nv, :nv] = cross
        return k
    return k_of


def _solve_level_batched(xv, yv, nodes, c, n_feat, kernel, gamma,
                         k_of=None, y_host=None, ell=None, solver="pg"):
    """One cascade level in node batches bounded by a byte budget.

    A level's vmapped solve holds ~3 (cap, cap) f32 buffers per node
    (K, Q, and GEMV temporaries); solving every node of a wide level at
    once would scale per-level memory with m.  Batches are padded to a
    fixed node count with all-invalid rows (C pinned to 0 → their alpha
    converges to 0 immediately) so only one shape per cap compiles.
    Sparse staging: ``ell`` gathers + densifies each node's rows ON
    DEVICE (no host product anywhere in the level); ``k_of`` is the
    host-CSR fallback that stages precomputed kernel blocks."""
    n_nodes, cap = nodes.shape
    # dense/ell paths also gather a (cap, n_feat) row block per node — at
    # n_feat >> cap that term, not the (cap, cap) buffers, bounds memory;
    # the ell gather adds the (cap, r) vals+cols staging buffers
    per_node = 3 * cap * cap * 4
    if k_of is None:
        per_node += cap * n_feat * 4
    if ell is not None:
        per_node += cap * ell[0].shape[1] * 8
    batch = min(n_nodes, max(1, _solve_budget() // per_node))

    def solve_chunk(chunk):
        if ell is not None:
            return _solve_level_ell(ell[0], ell[1], yv, jnp.asarray(chunk),
                                    c, n_feat, kernel, gamma, solver)
        if k_of is None:
            return _solve_level(xv, yv, jnp.asarray(chunk), c, n_feat,
                                kernel, gamma, solver)
        valid = chunk >= 0
        k_sub = k_of(chunk)
        y_sub = np.where(valid, y_host[np.maximum(chunk, 0)], 0.0) \
            .astype(np.float32)
        c_vec = np.where(valid, c, 0.0).astype(np.float32)
        import warnings
        with warnings.catch_warnings():
            # k_sub (the staged kernel rows, the level's dominant buffer)
            # has no same-shape output to alias, so XLA reports it
            # "not usable" for aliasing at lowering — donation still
            # releases its HBM for solver temporaries mid-program, which
            # is the point; silence exactly that advisory
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return _solve_level_k(jnp.asarray(k_sub), jnp.asarray(y_sub),
                                  jnp.asarray(c_vec), solver)

    if n_nodes <= batch and k_of is None:
        return solve_chunk(nodes)
    # batched level: the dispatch→read sequence pipelines through the
    # shared host-loop discipline — batch t's blocking reads run under
    # batch t+1's solve (one extra batch in flight), db/seq bit-equal by
    # construction; the routing is observable through the schedule
    # counter like every other overlap site
    from dislib_tpu.ops import overlap as _ov
    from dislib_tpu.utils import profiling as _prof
    sched = _ov.resolve()
    _prof.count_schedule("csvm_batches", sched)

    def fetch(i):
        chunk = nodes[i * batch:(i + 1) * batch]
        if chunk.shape[0] < batch:
            chunk = np.concatenate(
                [chunk, np.full((batch - chunk.shape[0], cap), -1, np.int64)])
        a, o = solve_chunk(chunk)
        # start the device→host DMA too, so consume()'s blocking read
        # finds the bytes already on their way
        for buf in (a, o):
            if hasattr(buf, "copy_to_host_async"):
                buf.copy_to_host_async()
        return a, o

    def consume(i, pair):
        return np.asarray(pair[0]), np.asarray(pair[1])

    res = _ov.host_pipeline(-(-n_nodes // batch), fetch, consume,
                            overlap=_ov.overlapped(sched))
    return (np.concatenate([a for a, _ in res])[:n_nodes],
            np.concatenate([o for _, o in res])[:n_nodes])


def _pack_nodes(rows):
    """Stack variable-length index rows into a (-1)-padded matrix whose cap
    is rounded up to a power of two — bounds the number of distinct shapes
    `_solve_level` ever compiles for to O(log n)."""
    cap = max(1, max(len(r) for r in rows))
    cap = 1 << (cap - 1).bit_length()
    out = np.full((len(rows), cap), -1, np.int64)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
    return out


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _gram(a, b, kernel, gamma):
    if kernel == "rbf":
        return jnp.exp(-gamma * distances_sq(a, b))
    return a @ b.T


def _use_fista() -> bool:
    """Solver policy: DSLIB_CSVM_SOLVER in {auto (default), pg, fista}.
    'fista' is accelerated projected gradient with adaptive restart —
    same fixed point, same stopping rule, typically several-fold fewer
    sequential while_loop steps, which is exactly the latency driver of
    the cascade on TPU (each step is one small GEMV).  'auto' currently
    keeps plain PG: flipping the default waits for the on-chip A/B the
    bench row now emits (the CholeskyQR2 precedent — policy changes ride
    measurements, not expectations)."""
    import os
    v = os.environ.get("DSLIB_CSVM_SOLVER", "auto")
    if v not in ("auto", "pg", "fista"):
        raise ValueError(
            f"DSLIB_CSVM_SOLVER={v!r} — expected auto, pg or fista")
    return v == "fista"


def _dual_ascent(q, c_vec, solver="pg"):
    """Box-constrained dual maximization on one node (shared by the
    gathered-rows and precomputed-K solvers).  ``solver``: 'pg' = plain
    projected gradient ascent; 'fista' = accelerated (Nesterov momentum,
    gradient-scheme adaptive restart so the momentum can never drive the
    objective backwards for long).  Identical stopping rule and step cap,
    so the two differ only in sequential-step count."""
    eta = 1.0 / jnp.maximum(jnp.max(jnp.sum(jnp.abs(q), axis=1)), 1e-12)
    alpha0 = jnp.zeros_like(c_vec)

    if solver == "fista":
        def body(carry):
            alpha, z, t, i, _ = carry
            grad = 1.0 - q @ z
            new = jnp.clip(z + eta * grad, 0.0, c_vec)
            # restart when the update opposes the momentum direction
            restart = jnp.sum((z - new) * (new - alpha)) > 0.0
            t_next = jnp.where(
                restart, 1.0, (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0)
            beta = jnp.where(restart, 0.0, (t - 1.0) / t_next)
            z_next = new + beta * (new - alpha)
            delta = jnp.max(jnp.abs(new - alpha))
            return new, z_next, t_next, i + 1, delta

        def cond(carry):
            _, _, _, i, delta = carry
            return (i < 500) & (delta > 1e-6)

        alpha, _, _, _, _ = lax.while_loop(
            cond, body, (alpha0, alpha0, jnp.float32(1.0), jnp.int32(0),
                         jnp.float32(jnp.inf)))
    else:
        def body(carry):
            alpha, i, _ = carry
            grad = 1.0 - q @ alpha
            new = jnp.clip(alpha + eta * grad, 0.0, c_vec)
            delta = jnp.max(jnp.abs(new - alpha))
            return new, i + 1, delta

        def cond(carry):
            _, i, delta = carry
            return (i < 500) & (delta > 1e-6)

        alpha, _, _ = lax.while_loop(cond, body, (alpha0, jnp.int32(0),
                                                  jnp.float32(jnp.inf)))
    # dual objective on the Q this solve already holds — callers read
    # the top node's value for the convergence check
    obj = jnp.sum(alpha) - 0.5 * alpha @ (q @ alpha)
    return alpha, obj


@partial(_pjit, static_argnames=("n_feat", "kernel", "solver"),
         name="csvm_solve_level")
@precise
def _solve_level(xv, yv, nodes, c, n_feat, kernel, gamma, solver):
    """Solve the boxed dual on every node of a cascade level (vmap).  Each
    node's (cap, cap) sub-Gram is built from its gathered rows — the m×m
    Gram is never materialised."""

    def solve_one(idx):
        valid = idx >= 0
        safe = jnp.maximum(idx, 0)
        x_sub = xv[safe, :n_feat]
        k_sub = _gram(x_sub, x_sub, kernel, gamma) + 1.0  # K+1 bias augment
        y_sub = yv[safe]
        q = k_sub * (y_sub[:, None] * y_sub[None, :])
        c_vec = jnp.where(valid, c, 0.0)            # padded slots pinned at 0
        return _dual_ascent(q, c_vec, solver)

    return jax.vmap(solve_one)(nodes)


@partial(_pjit, static_argnames=("n_feat",), name="csvm_ell_rows")
def _ell_rows_dense(ev, ec, idx, n_feat):
    """Densify the rows ``idx`` of an ELL-format sparse matrix on device:
    one scatter-add per gather — the device replacement for slicing a host
    CSR (`SparseArray.ell`)."""
    v = ev[idx]                                   # (cap, r)
    cc = ec[idx]
    cap, r = v.shape
    rows = jnp.broadcast_to(jnp.arange(cap)[:, None], (cap, r))
    return jnp.zeros((cap, n_feat), ev.dtype).at[rows, cc].add(v)


@partial(_pjit, static_argnames=("n_feat", "kernel", "solver"),
         name="csvm_solve_level_ell")
@precise
def _solve_level_ell(ev, ec, yv, nodes, c, n_feat, kernel, gamma, solver):
    """Boxed-dual solves with device-resident sparse staging: each node
    gathers its rows from the ELL buffers, densifies its (cap, n) block by
    scatter, and computes its (cap, cap) sub-Gram on device — the whole
    cascade level is one program, no host kernel products (the sparse
    analog of `_solve_level`)."""

    def solve_one(idx):
        valid = idx >= 0
        safe = jnp.maximum(idx, 0)
        x_sub = _ell_rows_dense(ev, ec, safe, n_feat)
        k_sub = _gram(x_sub, x_sub, kernel, gamma) + 1.0
        y_sub = yv[safe]
        q = k_sub * (y_sub[:, None] * y_sub[None, :])
        c_vec = jnp.where(valid, c, 0.0)
        return _dual_ascent(q, c_vec, solver)

    return jax.vmap(solve_one)(nodes)


# k_sub (per-node kernel rows) and y_sub are DONATED: both are staged
# fresh per call and dead afterwards; y_sub aliases the alpha output,
# k_sub frees the level's largest buffer for solver temporaries.
@partial(_pjit, static_argnames=("solver",),
         donate_argnames=("k_sub", "y_sub"), name="csvm_solve_level_k")
@precise
def _solve_level_k(k_sub, y_sub, c_vec, solver):
    """Same dual solves on host-staged kernel blocks (the sparse path)."""
    def solve_one(k1, y1, cv):
        q = (k1 + 1.0) * (y1[:, None] * y1[None, :])
        return _dual_ascent(q, cv, solver)
    return jax.vmap(solve_one)(k_sub, y_sub, c_vec)


@partial(_pjit, static_argnames=("kernel",), name="csvm_decision_sparse")
@precise
def _decision_sparse(bcoo, rowsq, sv_x, sv_y, sv_alpha, kernel, gamma):
    """Decision values for sparse queries: cross = one spmm (m, n_sv)."""
    from dislib_tpu.data.sparse import _spmm
    cross = _spmm(bcoo, sv_x.T)
    if kernel == "rbf":
        sv_sq = jnp.sum(sv_x * sv_x, axis=1)
        k = jnp.exp(-gamma * jnp.maximum(
            rowsq[:, None] - 2.0 * cross + sv_sq[None, :], 0.0))
    else:
        k = cross
    return ((k + 1.0) @ (sv_alpha * sv_y))[:, None]


def _decision_core(qp, q_shape, sv_x, sv_y, sv_alpha, kernel, gamma):
    mq, n = q_shape
    qv = qp[:, :n]
    if kernel == "rbf":
        k = jnp.exp(-gamma * distances_sq(qv, sv_x))
    else:
        k = qv @ sv_x.T
    dec = (k + 1.0) @ (sv_alpha * sv_y)
    valid = lax.broadcasted_iota(jnp.int32, (qv.shape[0],), 0) < mq
    return jnp.where(valid, dec, 0.0)[:, None]


def _decision_kernel(cfg, qp, sv_x, sv_y, sv_alpha, gamma):
    """`decision_function` as a fusion-node body (cfg = (q_shape, kernel);
    gamma rides as a dynamic operand so one program serves every gamma)."""
    q_shape, kernel = cfg
    return _decision_core(qp, q_shape, sv_x, sv_y, sv_alpha, kernel, gamma)


def _csvm_predict_kernel(cfg, qp, sv_x, sv_y, sv_alpha, gamma, classes):
    """`predict` as a fusion-node body: decision → threshold → on-device
    class-value lookup.  Padded rows re-zero (classes[0] may be nonzero)."""
    q_shape, kernel = cfg
    dec = _decision_core(qp, q_shape, sv_x, sv_y, sv_alpha, kernel, gamma)
    labels = jnp.where(dec > 0, classes[1], classes[0])
    valid = lax.broadcasted_iota(jnp.int32, labels.shape, 0) < q_shape[0]
    return jnp.where(valid, labels, jnp.zeros((), labels.dtype))
