"""k-nearest-neighbors classifier (reference: `dislib/classification/knn` —
vote over the k nearest, built on the NearestNeighbors machinery;
SURVEY.md §3.3).

TPU-native: neighbor search is the sharded distance GEMM + top_k of
`dislib_tpu.neighbors`; the vote is a one-hot sum + argmax on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.neighbors import base as _nb
from dislib_tpu.neighbors.base import _kneighbors
from dislib_tpu.ops.base import precise


class KNeighborsClassifier(BaseEstimator):
    """Majority-vote kNN classifier.

    Attributes
    ----------
    classes_ : ndarray of unique labels.
    """

    _private_fitted_attrs = ("_fit_x", "_codes")

    def __init__(self, n_neighbors=5, weights="uniform"):
        self.n_neighbors = n_neighbors
        self.weights = weights

    def fit(self, x: Array, y: Array):
        if x.shape[0] != y.shape[0]:
            raise ValueError("x and y row counts differ")
        self._fit_x = x
        yv = y.collect().ravel()
        self.classes_ = np.unique(yv)
        codes = np.searchsorted(self.classes_, yv).astype(np.int32)
        self._codes = jnp.asarray(codes)
        return self

    def _predict_codes(self, x: Array):
        """Winning class codes per query row, (mq_pad-or-mq, 1) int32.
        Sparse fit/query routes through the sparse-native neighbor search
        (no whole-matrix densification), then votes on its (dist, idx)."""
        from dislib_tpu.data.sparse import SparseArray
        f = self._fit_x
        if isinstance(f, SparseArray) or isinstance(x, SparseArray):
            from dislib_tpu.neighbors.base import _kneighbors_sparse
            dist_k, idx = _kneighbors_sparse(x, f, self.n_neighbors)
            return _knn_vote(dist_k, idx, self._codes, len(self.classes_),
                             self.weights == "distance")
        return _knn_predict(x._data, f._data, x.shape, f.shape, self._codes,
                            len(self.classes_), self.n_neighbors,
                            self.weights == "distance", _nb._CHUNK)

    def predict(self, x: Array) -> Array:
        self._check_fitted()
        if self.weights not in ("uniform", "distance"):
            raise ValueError(f"bad weights {self.weights!r}")
        if self.n_neighbors > self._fit_x.shape[0]:
            raise ValueError(f"n_neighbors {self.n_neighbors} > fitted samples "
                             f"{self._fit_x.shape[0]}")
        # the device kernel votes in int32 code space; class values are
        # mapped on host so integer labels never round-trip through float32
        codes = self._predict_codes(x)
        labels = self.classes_[np.asarray(jax.device_get(codes)).ravel()
                               [: x.shape[0]]]
        dt = np.int32 if np.issubdtype(labels.dtype, np.integer) else np.float32
        out = jnp.asarray(labels.astype(dt)[:, None])
        from dislib_tpu.data.array import _repad
        return Array._from_logical_padded(_repad(out, (x.shape[0], 1)),
                                          (x.shape[0], 1))

    def score(self, x: Array, y: Array) -> float:
        pred = self.predict(x).collect().ravel()
        return float((pred == y.collect().ravel()).mean())

    # async trial protocol (SURVEY §4.5): the fit is host-side input prep
    # (class codes); the heavy work is the predict/score program, which
    # _score_async returns as a device scalar so GridSearchCV pipelines all
    # trials' kNN GEMMs before reading any accuracy back
    def _fit_async(self, x, y=None):
        if y is None:
            raise ValueError("KNeighborsClassifier requires y")
        self.fit(x, y)
        # sentinel only: the real state lives in self._fit_x/self._codes;
        # a non-None return tells the search the async path is live
        return "fitted"

    def _score_async(self, state, x, y=None):
        if state is None or y is None:
            return super()._score_async(state, x, y)
        if self.weights not in ("uniform", "distance"):
            raise ValueError(f"bad weights {self.weights!r}")
        if self.n_neighbors > self._fit_x.shape[0]:
            raise ValueError(f"n_neighbors {self.n_neighbors} > fitted "
                             f"samples {self._fit_x.shape[0]}")
        from dislib_tpu.data.sparse import SparseArray
        # compare in y's backing dtype (not a forced float32): classes_ come
        # from the same storage pipeline as y, so they are distinct in that
        # dtype and code mapping is collision-free (f64 labels under x64
        # mode included)
        classes_dev = jnp.asarray(np.asarray(self.classes_),
                                  dtype=y._data.dtype)
        if isinstance(self._fit_x, SparseArray) or isinstance(x, SparseArray):
            pred = self._predict_codes(x)
            return _score_codes(pred, y._data, classes_dev, x.shape[0])
        return _knn_score(x._data, self._fit_x._data, y._data, x.shape,
                          self._fit_x.shape, self._codes, classes_dev,
                          self.n_neighbors, self.weights == "distance",
                          _nb._CHUNK)

    def _check_fitted(self):
        if not hasattr(self, "_fit_x"):
            raise RuntimeError("KNeighborsClassifier is not fitted")


def _vote(dist_k, idx, codes, n_classes, use_dist):
    """Winner class code per row from (dist, idx) neighbor lists."""
    neigh_codes = codes[idx]                                  # (rows, k)
    onehot = jax.nn.one_hot(neigh_codes, n_classes, dtype=jnp.float32)
    if use_dist:
        wts = 1.0 / jnp.maximum(dist_k, 1e-10)
        votes = jnp.sum(onehot * wts[:, :, None], axis=1)
    else:
        votes = jnp.sum(onehot, axis=1)
    return jnp.argmax(votes, axis=1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_classes", "use_dist"))
@precise
def _knn_vote(dist_k, idx, codes, n_classes, use_dist):
    return _vote(dist_k, idx, codes, n_classes, use_dist)[:, None]


def _codes_of(yv, classes_dev):
    """Map label values into class-code space; a round-trip equality check
    marks labels unseen at fit time (they can never count as correct)."""
    n_classes = classes_dev.shape[0]
    yc = jnp.clip(jnp.searchsorted(classes_dev, yv), 0, n_classes - 1) \
        .astype(jnp.int32)
    return yc, classes_dev[yc] == yv


@partial(jax.jit, static_argnames=("mq",))
def _score_codes(pred, yp, classes_dev, mq):
    """Device accuracy from predicted class codes."""
    yv = yp[: pred.shape[0], 0].astype(classes_dev.dtype)
    yc, seen = _codes_of(yv, classes_dev)
    valid = lax.broadcasted_iota(jnp.int32, (pred.shape[0],), 0) < mq
    hits = jnp.sum((pred[:, 0] == yc) & seen & valid)
    return hits.astype(jnp.float32) / mq


@partial(jax.jit, static_argnames=("q_shape", "f_shape", "k", "use_dist",
                                   "chunk"))
@precise
def _knn_score(qp, fp, yp, q_shape, f_shape, codes, classes_dev, k, use_dist,
               chunk):
    """Device accuracy: predicted class codes vs y mapped into code space.
    Unseen validation labels (not in classes_) can never count as correct —
    the round-trip check classes_[y_code] == y guards the searchsorted
    collision."""
    n_classes = classes_dev.shape[0]
    pred = _knn_predict(qp, fp, q_shape, f_shape, codes, n_classes, k,
                        use_dist, chunk)
    return _score_codes(pred, yp, classes_dev, q_shape[0])


@partial(jax.jit, static_argnames=("q_shape", "f_shape", "n_classes", "k",
                                   "use_dist", "chunk"))
@precise
def _knn_predict(qp, fp, q_shape, f_shape, codes, n_classes, k, use_dist,
                 chunk):
    dist_k, idx = _kneighbors(qp, fp, q_shape, f_shape, k, chunk=chunk)
    winner = _vote(dist_k, idx, codes, n_classes, use_dist)
    mq = q_shape[0]
    valid = lax.broadcasted_iota(jnp.int32, (winner.shape[0],), 0) < mq
    return jnp.where(valid, winner, 0)[:, None]
