"""k-nearest-neighbors classifier (reference: `dislib/classification/knn` —
vote over the k nearest, built on the NearestNeighbors machinery;
SURVEY.md §3.3).

TPU-native: neighbor search is the sharded distance GEMM + top_k of
`dislib_tpu.neighbors`; the vote is a one-hot sum + argmax on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.neighbors import base as _nb
from dislib_tpu.neighbors.base import _kneighbors
from dislib_tpu.ops.base import precise


class KNeighborsClassifier(BaseEstimator):
    """Majority-vote kNN classifier.

    Attributes
    ----------
    classes_ : ndarray of unique labels.
    """

    _private_fitted_attrs = ("_fit_x", "_codes")

    def __init__(self, n_neighbors=5, weights="uniform"):
        self.n_neighbors = n_neighbors
        self.weights = weights

    def fit(self, x: Array, y: Array):
        if x.shape[0] != y.shape[0]:
            raise ValueError("x and y row counts differ")
        self._fit_x = x
        yv = y.collect().ravel()
        self.classes_ = np.unique(yv)
        codes = np.searchsorted(self.classes_, yv).astype(np.int32)
        self._codes = jnp.asarray(codes)
        return self

    def predict(self, x: Array) -> Array:
        self._check_fitted()
        if self.weights not in ("uniform", "distance"):
            raise ValueError(f"bad weights {self.weights!r}")
        if self.n_neighbors > self._fit_x.shape[0]:
            raise ValueError(f"n_neighbors {self.n_neighbors} > fitted samples "
                             f"{self._fit_x.shape[0]}")
        # the device kernel votes in int32 code space; class values are
        # mapped on host so integer labels never round-trip through float32
        codes = _knn_predict(x._data, self._fit_x._data, x.shape,
                             self._fit_x.shape, self._codes,
                             len(self.classes_), self.n_neighbors,
                             self.weights == "distance", _nb._CHUNK)
        labels = self.classes_[np.asarray(jax.device_get(codes)).ravel()
                               [: x.shape[0]]]
        dt = np.int32 if np.issubdtype(labels.dtype, np.integer) else np.float32
        out = jnp.asarray(labels.astype(dt)[:, None])
        from dislib_tpu.data.array import _repad
        return Array._from_logical_padded(_repad(out, (x.shape[0], 1)),
                                          (x.shape[0], 1))

    def score(self, x: Array, y: Array) -> float:
        pred = self.predict(x).collect().ravel()
        return float((pred == y.collect().ravel()).mean())

    def _check_fitted(self):
        if not hasattr(self, "_fit_x"):
            raise RuntimeError("KNeighborsClassifier is not fitted")


@partial(jax.jit, static_argnames=("q_shape", "f_shape", "n_classes", "k",
                                   "use_dist", "chunk"))
@precise
def _knn_predict(qp, fp, q_shape, f_shape, codes, n_classes, k, use_dist,
                 chunk):
    dist_k, idx = _kneighbors(qp, fp, q_shape, f_shape, k, chunk=chunk)
    neigh_codes = codes[idx]                                  # (mq_pad, k)
    onehot = jax.nn.one_hot(neigh_codes, n_classes, dtype=jnp.float32)
    if use_dist:
        wts = 1.0 / jnp.maximum(dist_k, 1e-10)
        votes = jnp.sum(onehot * wts[:, :, None], axis=1)
    else:
        votes = jnp.sum(onehot, axis=1)
    winner = jnp.argmax(votes, axis=1).astype(jnp.int32)
    mq = q_shape[0]
    valid = lax.broadcasted_iota(jnp.int32, (winner.shape[0],), 0) < mq
    return jnp.where(valid, winner, 0)[:, None]
