from dislib_tpu.classification.knn import KNeighborsClassifier

__all__ = ["KNeighborsClassifier"]
