from dislib_tpu.classification.knn import KNeighborsClassifier
from dislib_tpu.classification.csvm import CascadeSVM

__all__ = ["KNeighborsClassifier", "CascadeSVM"]
