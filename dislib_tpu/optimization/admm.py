"""Consensus ADMM (reference: `dislib/optimization/admm` — generic driver
with distributed per-partition x-updates as tasks, global z-update with
soft-thresholding on master, dual updates, primal/dual residual convergence;
SURVEY.md §3.3).

TPU-native redesign: the per-partition agents ARE the mesh row shards.  One
`shard_map` runs the whole ADMM iteration loop on device:

    local:      x_i = (A_iᵀA_i + ρI)⁻¹ (A_iᵀb_i + ρ(z − u_i))   (Cholesky,
                factorised once outside the loop)
    collective: z̄ = mean_i(x_i + u_i)        — one psum over 'rows'
    local:      z = prox(z̄),  u_i += x_i − z

The reference's per-iteration master round-trip for the z-update becomes an
all-reduce over ICI; convergence (primal ‖x_i−z‖ via psum, dual ρ‖z−z_old‖)
is evaluated on device inside the while_loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise
from dislib_tpu.utils.dlog import verbose_logger


def soft_threshold(v, k):
    """Soft-thresholding operator S_k(v) — the L1 prox."""
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - k, 0.0)


def identity_prox(v, k):
    return v


class ADMM(BaseEstimator):
    """Generic consensus ADMM driver.

    Parameters
    ----------
    z_prox : callable(z_mean, kappa) -> z — the global prox step (identity if
        None).  Pass a MODULE-LEVEL function (e.g. :func:`soft_threshold`):
        the prox is a static jit argument, so a fresh closure per fit would
        recompile the whole ADMM loop every call.  Per-fit scalars go in
        ``prox_kappa`` (a traced operand).
    prox_kappa : float — scalar handed to ``z_prox`` (e.g. the L1 threshold).
    rho : float — augmented-Lagrangian penalty.
    max_iter, abstol, reltol : convergence controls (reference parity:
        `max_iter`, `atol`, `rtol`).

    Attributes
    ----------
    z_ : ndarray (n_features,) — consensus solution.
    n_iter_ : int ;  converged_ : bool
    history_ : ndarray (n_iter_,) — per-iteration primal residual (SURVEY §6).
    """

    def __init__(self, z_prox=None, prox_kappa=0.0, rho=1.0, max_iter=100,
                 abstol=1e-4, reltol=1e-2, verbose=False):
        self.z_prox = z_prox
        self.prox_kappa = prox_kappa
        self.rho = rho
        self.max_iter = max_iter
        self.abstol = abstol
        self.reltol = reltol
        self.verbose = verbose

    def fit(self, x: Array, y: Array):
        """Solve consensus least-squares + prox over row-partitions of (x, y)."""
        self._fit_finalize(self._fit_async(x, y))
        return self

    # async trial protocol (SURVEY §4.5): the whole consensus loop is one
    # shard_map program; the handle is its device output tuple
    def _fit_async(self, x: Array, y: Array):
        if y.shape[1] != 1:
            raise ValueError(f"ADMM supports a single target column; y is {y.shape}")
        if x.shape[0] != y.shape[0]:
            raise ValueError(f"x and y row counts differ: {x.shape[0]} != {y.shape[0]}")
        prox = self.z_prox if self.z_prox is not None else identity_prox
        return _admm_fit(
            x._data, y._data, x.shape, (y.shape[0], y.shape[1]),
            float(self.rho), jnp.float32(self.prox_kappa),
            float(self.abstol), float(self.reltol),
            self.max_iter, prox, _mesh.get_mesh())

    def _fit_finalize(self, state):
        if state is None:
            return
        z, n_iter, converged, hist = state
        self.z_ = np.asarray(jax.device_get(z)).ravel()
        self.n_iter_ = int(n_iter)
        self.converged_ = bool(converged)
        self.history_ = np.asarray(
            jax.device_get(hist), dtype=np.float64)[: self.n_iter_]
        verbose_logger("admm", self.verbose).info(
            "converged=%s n_iter=%d primal_residual=%.3g", self.converged_,
            self.n_iter_, self.history_[-1] if len(self.history_) else np.nan)


@partial(jax.jit, static_argnames=("x_shape", "y_shape", "max_iter", "prox", "mesh"))
@precise
def _admm_fit(xp, yp, x_shape, y_shape, rho, kappa, abstol, reltol, max_iter, prox, mesh):
    m, n = x_shape
    xv = xp[:, :n]
    yv = yp[:, : y_shape[1]]
    p = mesh.shape[_mesh.ROWS]

    def agent(a_i, b_i):
        # Cholesky factor of (A_iᵀA_i + ρI), once
        ata = a_i.T @ a_i + rho * jnp.eye(n, dtype=a_i.dtype)
        chol = jnp.linalg.cholesky(ata)
        atb = (a_i.T @ b_i)[:, 0]

        def solve(rhs):
            w = jax.scipy.linalg.solve_triangular(chol, rhs, lower=True)
            return jax.scipy.linalg.solve_triangular(chol.T, w, lower=False)

        def step(carry):
            x_i, z, u_i, _, _, it, hist = carry
            x_i = solve(atb + rho * (z - u_i))
            z_old = z
            zbar = lax.pmean(x_i + u_i, _mesh.ROWS)
            z = prox(zbar, kappa)
            u_i = u_i + x_i - z
            # residuals (global)
            r = jnp.sqrt(lax.psum(jnp.sum((x_i - z) ** 2), _mesh.ROWS))
            s = rho * jnp.sqrt(jnp.asarray(p, x_i.dtype)) * jnp.linalg.norm(z - z_old)
            e_pri = (jnp.sqrt(jnp.asarray(n * p, x_i.dtype)) * abstol + reltol *
                     jnp.maximum(jnp.sqrt(lax.psum(jnp.sum(x_i ** 2), _mesh.ROWS)),
                                 jnp.sqrt(jnp.asarray(p, x_i.dtype)) * jnp.linalg.norm(z)))
            e_dual = (jnp.sqrt(jnp.asarray(n * p, x_i.dtype)) * abstol + reltol *
                      jnp.sqrt(lax.psum(jnp.sum((rho * u_i) ** 2), _mesh.ROWS)))
            conv = (r < e_pri) & (s < e_dual)
            return x_i, z, u_i, conv, r, it + 1, hist.at[it].set(r)

        def cond(carry):
            _, _, _, conv, _, it, _ = carry
            return (~conv) & (it < max_iter)

        zeros = jnp.zeros((n,), xv.dtype)
        # x_i/u_i are shard-varying through the loop; mark the (constant)
        # initial values varying too so the carry's vma types line up and
        # replication checking can stay ON for the whole shard_map
        x0 = lax.pcast(zeros, _mesh.ROWS, to="varying")
        u0 = lax.pcast(zeros, _mesh.ROWS, to="varying")
        x_i, z, u_i, conv, _, it, hist = lax.while_loop(
            cond, step, (x0, zeros, u0, jnp.asarray(False),
                         jnp.asarray(0.0, xv.dtype), jnp.int32(0),
                         jnp.zeros((max_iter,), xv.dtype)))
        return z[None, :], it, conv, hist

    z, it, conv, hist = jax.shard_map(
        agent, mesh=mesh,
        in_specs=(P(_mesh.ROWS, None), P(_mesh.ROWS, None)),
        out_specs=(P(None, None), P(), P(), P()),
        check_vma=True,
    )(xv, yv)
    return z[0], it, conv, hist
