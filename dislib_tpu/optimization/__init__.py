from dislib_tpu.optimization.admm import ADMM, soft_threshold

__all__ = ["ADMM", "soft_threshold"]
