"""Random forests + single decision trees (reference: `dislib/trees/forest.py`
— `RandomForestClassifier(n_estimators, try_features, max_depth, distr_depth,
sklearn_max, hard_vote, random_state)`, `RandomForestRegressor`; SURVEY.md
§3.3).  Growth machinery in `decision_tree.py`; here the sklearn-style API,
label handling and voting."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.data.array import (Array, _padded_dim, _place_region,
                                   ensure_canonical, fused_kernel)
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.trees.decision_tree import (_BaseTreeEnsemble,
                                            _forest_apply, _forest_apply_core,
                                            _pack_levels)


def _cls_enc(counts, hard):
    """Winning class code per query from per-tree leaf counts (T, m, K) —
    the single vote implementation shared by predict and the async score
    kernel (they must never diverge)."""
    if hard:
        votes = jnp.argmax(counts, axis=2)                  # (T, m)
        tally = jax.nn.one_hot(votes, counts.shape[2]).sum(axis=0)
        return jnp.argmax(tally, axis=1)
    probs = counts / jnp.maximum(
        jnp.sum(counts, axis=2, keepdims=True), 1e-12)
    return jnp.argmax(jnp.mean(probs, axis=0), axis=1)


def _reg_mean(stats):
    """Forest-mean prediction from per-tree leaf [w, wy, wy²] stats."""
    return jnp.mean(stats[:, :, 1] / jnp.maximum(stats[:, :, 0], 1e-12),
                    axis=0)


class _ClassifierMixin:
    _criterion = "gini"

    def _encode_labels(self, x: Array, y: Array):
        # cached on the y Array per (kind, padding): a grid search encodes
        # each fold once, not once per candidate (the encode is a full
        # y.collect() — a DCN allgather on multi-host)
        mp = x._data.shape[0]
        cached = getattr(y, "_tree_enc_cache", None)
        if cached is not None and cached[0] == ("cls", mp):
            self.classes_ = cached[1]
            return cached[2]
        y_host = np.asarray(y.collect()).ravel()
        self.classes_ = np.unique(y_host)
        enc = np.searchsorted(self.classes_, y_host)
        k = len(self.classes_)
        onehot = np.zeros((mp, k), np.float32)
        onehot[np.arange(len(enc)), enc] = 1.0
        y._tree_enc_cache = (("cls", mp), self.classes_, onehot)
        return onehot

    def predict_proba(self, x: Array) -> Array:
        self._check_fitted()
        # serve on the CURRENT mesh: an input built before an elastic
        # resize re-lands on device (never the host) — round 16
        x = ensure_canonical(x)
        k = len(self.classes_)
        out_pshape = (x._pshape[0], _padded_dim(k, _mesh.pad_quantum()))
        edges, feats, tbins, leaves = self._predict_leaves(
            self._edges, self._feats, self._tbins, self._leaves)
        return fused_kernel(
            _forest_proba_kernel, (x.shape, self._depth, out_pshape),
            (x, edges, feats, tbins, leaves),
            (x.shape[0], k), jnp.float32, out_pshape=out_pshape)

    def predict(self, x: Array) -> Array:
        """Class label per row — one fusion node: the gather-walk apply,
        the vote, AND the class-value lookup all on device (the old host
        round-trip between vote and label selection was a hidden
        per-predict sync; integer classes stay int32, exact to 2^31 where
        float32 corrupts past 2^24 — VERDICT r1 weak #8)."""
        self._check_fitted()
        x = ensure_canonical(x)     # serve on the CURRENT mesh (round 16)
        classes = self._classes_leaf()
        edges, feats, tbins, leaves, classes_dev = self._predict_leaves(
            self._edges, self._feats, self._tbins, self._leaves, classes)
        return fused_kernel(
            _forest_cls_predict_kernel,
            (x.shape, self._depth, bool(getattr(self, "hard_vote", False))),
            (x, edges, feats, tbins, leaves, classes_dev),
            (x.shape[0], 1), classes_dev.dtype,
            out_pshape=(x._pshape[0], 1))

    def score(self, x: Array, y: Array) -> float:
        pred = self.predict(x).collect().ravel()
        truth = np.asarray(y.collect()).ravel()
        return float(np.mean(pred == truth))

    _encode_stats = _encode_labels

    def _score_async(self, state, x, y=None):
        if state is None or y is None:
            return super()._score_async(state, x, y)
        classes_dev = jnp.asarray(np.asarray(self.classes_),
                                  dtype=y._data.dtype)
        return _cls_score_kernel(
            x._data, x.shape, jnp.asarray(state["edges"]), state["feats"],
            state["tbins"], state["depth"], state["leaves"], classes_dev,
            bool(getattr(self, "hard_vote", False)), y._data, x.shape[0])


class _RegressorMixin:
    _criterion = "mse"

    def _encode_targets(self, x: Array, y: Array):
        mp = x._data.shape[0]
        cached = getattr(y, "_tree_enc_cache", None)
        if cached is not None and cached[0] == ("reg", mp):
            return cached[1]
        y_host = np.asarray(y.collect()).ravel().astype(
            px.compute_dtype(px.FLOAT32))
        stats = np.zeros((mp, 3), np.float32)               # [w, wy, wy²] basis
        stats[: len(y_host), 0] = 1.0
        stats[: len(y_host), 1] = y_host
        stats[: len(y_host), 2] = y_host * y_host
        y._tree_enc_cache = (("reg", mp), stats)
        return stats

    def predict(self, x: Array) -> Array:
        self._check_fitted()
        x = ensure_canonical(x)     # serve on the CURRENT mesh (round 16)
        edges, feats, tbins, leaves = self._predict_leaves(
            self._edges, self._feats, self._tbins, self._leaves)
        return fused_kernel(
            _forest_reg_predict_kernel, (x.shape, self._depth),
            (x, edges, feats, tbins, leaves),
            (x.shape[0], 1), jnp.float32, out_pshape=(x._pshape[0], 1))

    def score(self, x: Array, y: Array) -> float:
        """R² (sklearn convention)."""
        pred = self.predict(x).collect().ravel()
        truth = np.asarray(y.collect()).ravel()
        ss_res = float(np.sum((truth - pred) ** 2))
        ss_tot = float(np.sum((truth - truth.mean()) ** 2))
        return 1.0 - ss_res / max(ss_tot, 1e-12)

    _encode_stats = _encode_targets

    def _score_async(self, state, x, y=None):
        if state is None or y is None:
            return super()._score_async(state, x, y)
        return _reg_score_kernel(
            x._data, x.shape, jnp.asarray(state["edges"]), state["feats"],
            state["tbins"], state["depth"], state["leaves"], y._data,
            x.shape[0])


class RandomForestClassifier(_ClassifierMixin, _BaseTreeEnsemble):
    """Bootstrap ensemble of histogram decision trees (classification).

    Parameters (reference parity; `distr_depth`, `sklearn_max` accepted and
    ignored — see decision_tree module docstring)
    ----------
    n_estimators : int, default 10
    try_features : 'sqrt' (default), 'third', int, or None (all features)
    max_depth : int or np.inf — clamped to 12 (padded-array level cap; a
        finite request above the cap warns).
    hard_vote : bool, default False — majority of per-tree votes instead of
        averaged probabilities.
    random_state : int or None
    n_bins : int, default 32 — split thresholds per feature are quantile
        bin edges (histogram trees; the reference's sklearn delegation
        searches exact thresholds instead). Raise for data whose class
        structure is finer than ~1/n_bins quantile spacing.
    """

    def __init__(self, n_estimators=10, try_features="sqrt", max_depth=np.inf,
                 distr_depth="auto", sklearn_max=1e8, hard_vote=False,
                 random_state=None, n_bins=32):
        self.n_estimators = n_estimators
        self.try_features = try_features
        self.max_depth = max_depth
        self.distr_depth = distr_depth
        self.sklearn_max = sklearn_max
        self.hard_vote = hard_vote
        self.random_state = random_state
        self.n_bins = n_bins

    def _fit_spec(self):
        return self.n_estimators, True


class RandomForestRegressor(_RegressorMixin, _BaseTreeEnsemble):
    """Bootstrap ensemble of histogram decision trees (regression).

    Same knobs as :class:`RandomForestClassifier` minus `hard_vote`.
    """

    def __init__(self, n_estimators=10, try_features="sqrt", max_depth=np.inf,
                 distr_depth="auto", sklearn_max=1e8, random_state=None,
                 n_bins=32):
        self.n_estimators = n_estimators
        self.try_features = try_features
        self.max_depth = max_depth
        self.distr_depth = distr_depth
        self.sklearn_max = sklearn_max
        self.random_state = random_state
        self.n_bins = n_bins

    def _fit_spec(self):
        return self.n_estimators, True


class DecisionTreeClassifier(_ClassifierMixin, _BaseTreeEnsemble):
    """Single histogram decision tree (no bootstrap, all features)."""

    def __init__(self, max_depth=np.inf, try_features=None, random_state=None,
                 n_bins=32):
        self.max_depth = max_depth
        self.try_features = try_features
        self.random_state = random_state
        self.n_bins = n_bins

    def _fit_spec(self):
        return 1, False


class DecisionTreeRegressor(_RegressorMixin, _BaseTreeEnsemble):
    """Single histogram regression tree (no bootstrap, all features)."""

    def __init__(self, max_depth=np.inf, try_features=None, random_state=None,
                 n_bins=32):
        self.max_depth = max_depth
        self.try_features = try_features
        self.random_state = random_state
        self.n_bins = n_bins

    def _fit_spec(self):
        return 1, False


# ---------------------------------------------------------------------------
# fused predict bodies (data.array.fused_kernel nodes — one dispatch for a
# whole scaler → forest pipeline; round-9 serving PR)
# ---------------------------------------------------------------------------

def _forest_votes(qp, q_shape, edges, feats, tbins, leaves, depth):
    """apply + per-tree leaf-stat gather: (T, mq_pad, S)."""
    leaf = _forest_apply_core(qp, q_shape, edges, feats, tbins, depth)
    return jnp.take_along_axis(leaves, leaf[:, :, None], axis=1)


def _mask_rows(vals, m):
    """Zero rows at or past the logical row count (padded rows walk the
    trees too and land in SOME leaf — their votes must not escape)."""
    valid = lax.broadcasted_iota(jnp.int32, (vals.shape[0], 1), 0) < m
    return jnp.where(valid, vals, jnp.zeros((), vals.dtype))


def _forest_cls_predict_kernel(cfg, qp, edges, feats, tbins, leaves, classes):
    q_shape, depth, hard = cfg
    counts = _forest_votes(qp, q_shape, edges, feats, tbins, leaves, depth)
    enc = _cls_enc(counts, hard)
    return _mask_rows(classes[enc][:, None], q_shape[0])


def _forest_proba_kernel(cfg, qp, edges, feats, tbins, leaves):
    q_shape, depth, out_pshape = cfg
    counts = _forest_votes(qp, q_shape, edges, feats, tbins, leaves, depth)
    probs = counts / jnp.maximum(
        jnp.sum(counts, axis=2, keepdims=True), 1e-12)
    mean = _mask_rows(jnp.mean(probs, axis=0), q_shape[0])  # (mq_pad, K)
    return _place_region(mean, out_pshape)


def _forest_reg_predict_kernel(cfg, qp, edges, feats, tbins, leaves):
    q_shape, depth = cfg
    stats = _forest_votes(qp, q_shape, edges, feats, tbins, leaves, depth)
    return _mask_rows(_reg_mean(stats)[:, None], q_shape[0])


# ---------------------------------------------------------------------------
# device scoring kernels for the async trial protocol (SURVEY §4.5)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("shape", "depth", "hard", "mq"))
def _cls_score_kernel(xp, shape, edges, feats, tbins, depth, leaves,
                      classes_dev, hard, yp, mq):
    """Device accuracy of a grown classification forest: apply + the shared
    `_cls_enc` vote, scored by knn's `_score_codes` (labels compared in
    y's backing dtype — collision-free)."""
    from dislib_tpu.classification.knn import _score_codes
    leaf = _forest_apply(xp, shape, edges, _pack_levels(feats, depth),
                         _pack_levels(tbins, depth), depth)
    counts = jnp.take_along_axis(leaves, leaf[:, :, None], axis=1)
    enc = _cls_enc(counts, hard).astype(jnp.int32)
    return _score_codes(enc[:, None], yp, classes_dev, mq)


@partial(jax.jit, static_argnames=("shape", "depth", "mq"))
def _reg_score_kernel(xp, shape, edges, feats, tbins, depth, leaves, yp, mq):
    """Device R² of a grown regression forest."""
    leaf = _forest_apply(xp, shape, edges, _pack_levels(feats, depth),
                         _pack_levels(tbins, depth), depth)
    stats = jnp.take_along_axis(leaves, leaf[:, :, None], axis=1)
    pred = _reg_mean(stats)                                 # (mq_pad,)
    yv = yp[: pred.shape[0], 0]
    w = (lax.broadcasted_iota(jnp.int32, (pred.shape[0],), 0) < mq) \
        .astype(yv.dtype)
    resid = jnp.sum(((yv - pred) * w) ** 2)
    ymean = jnp.sum(yv * w) / mq
    total = jnp.sum(((yv - ymean) * w) ** 2)
    return 1.0 - resid / jnp.maximum(total, 1e-12)
