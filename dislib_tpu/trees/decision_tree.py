"""Distributed decision trees (reference: `dislib/trees/decision_tree.py` +
`test_split.py` — top `distr_depth` levels split via `_compute_split` tasks,
subtrees delegated to one sklearn tree per task, file-based bootstrap-sample
side channel; SURVEY.md §3.3 "largest estimator subsystem").

TPU-native redesign — histogram trees, not sklearn delegation (SURVEY §8 M5):

- **Level-synchronous growth over padded node arrays.**  A tree of depth D is
  a heap-shaped array of 2^D − 1 internal nodes + 2^D leaves, grown one level
  at a time; every sample carries its current node id.  Data-dependent
  structure (the reference's recursive splits) becomes fixed-shape tensor
  ops: one (node, feature, bin) weighted histogram per level — a single
  scatter-add — then a vectorised best-gain argmax.  Nodes that stop
  splitting become pass-through splits (threshold +inf) so shapes never
  change.
- **Feature bins** are per-feature quantile thresholds (n_bins=32) computed
  once per fit; splits search bin boundaries, exactly the
  histogram-of-gradients trick GPU boosters use, and the analog of the
  reference's per-feature candidate-threshold search in `test_split.py`.
- **Bootstrap via Poisson(1) sample weights** per (tree, sample) — the
  dense-weights equivalent of the reference's per-tree bootstrap-index files
  (its shared-FS `.npy` side channel, SURVEY §3.3), with no random access.
- The whole forest grows together: every level is ONE jitted call `vmap`-ed
  over trees (the reference's task-per-tree parallelism, recovered as
  batching on the MXU).

`distr_depth` / `sklearn_max` are accepted for parity and ignored — they
tuned the task-distribution/delegation boundary, which doesn't exist here.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad
from dislib_tpu.ops import precision as px
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.profiling import profiled_jit as _pjit
from dislib_tpu.runtime import fetch as _fetch, repad_rows as _repad_rows
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health

# Discretisation contract (documented divergence from the reference, which
# delegates subtrees to exact sklearn trees with arbitrary thresholds):
# split thresholds are per-feature QUANTILE bin edges, `n_bins` per feature
# (constructor param, default N_BINS).  Distributions whose class/target
# structure lives at finer granularity than ~1/n_bins quantile spacing need
# a larger `n_bins` — see tests/test_trees.py::test_n_bins_contract for a
# distribution where 32 bins provably loses and n_bins=256 recovers it.
N_BINS = 32
# Depth is capped: node arrays are heap-shaped (2^depth), so depth is a
# compiled SHAPE — the cap keeps the padded arrays (and XLA programs)
# bounded.  Requesting a finite max_depth above the cap warns loudly
# (_effective_depth); the reference's data-bounded recursion has no cap.
MAX_DEPTH_CAP = 12


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("shape", "n_bins"))
def _quantile_bins(xp, shape, n_bins=N_BINS):
    """Per-feature bin edges from quantiles of the valid rows: (n, n_bins-1)."""
    m, n = shape
    xv = xp[:m, :n]
    qs = jnp.linspace(0.0, 100.0, n_bins + 1)[1:-1]
    return jnp.percentile(xv, qs, axis=0).T          # (n, n_bins-1)


@partial(jax.jit, static_argnames=("shape",))
def _bin_data(xp, shape, edges):
    """Bin index of every (sample, feature): (m_pad, n) int32 in [0, n_bins),
    with n_bins implied by the edges width (n, n_bins-1)."""
    n = shape[1]
    xv = xp[:, :n]
    # bx[i, f] = #edges below x[i, f]
    return jnp.sum(xv[:, :, None] > edges[None, :, :], axis=2).astype(jnp.int32)


def _node_histogram(node, bx, w, stats, n_nodes, n_bins, hist="xla"):
    """Per-sample `stats` (m, S) histogrammed into (n_nodes, n, n_bins,
    S).  ``hist`` is the schedule (a jit static resolved ONCE at the
    forest-fit boundary, `hist:<sched>` counter): "xla" is the plain
    scatter-add; "pallas" routes the one-hot-GEMM Pallas kernel
    (``ops/pallas_kernels.node_histogram``) — bit-equal here because the
    forest's contributions (Poisson weights × count/target stats) are
    integer-representable, so the sums are exact under either order."""
    if hist == "pallas":
        from dislib_tpu.ops import pallas_kernels as _pk
        return _pk.node_histogram(node, bx, w[:, None] * stats,
                                  n_nodes, n_bins).astype(
            px.compute_dtype(px.FLOAT32))
    m, n = bx.shape
    acc_dt = px.compute_dtype(px.FLOAT32)
    feat = lax.broadcasted_iota(jnp.int32, (m, n), 1)
    hist_acc = jnp.zeros((n_nodes, n, n_bins, stats.shape[1]), acc_dt)
    contrib = (w[:, None, None] * stats[:, None, :]).astype(acc_dt)
    contrib = jnp.broadcast_to(contrib, (m, n, stats.shape[1]))
    return hist_acc.at[node[:, None], feat, bx].add(contrib)


def _gain_and_split(hist, criterion):
    """Best (feature, bin) per node from the level histogram.

    hist: (n_nodes, n, N_BINS, S).  Returns (feat, bin, gain, node_total)
    where node_total is the per-node stats vector (S,).
    criterion: 'gini' (S = n_classes counts) or 'mse' (S = [w, wy, wy²]).
    """
    left = jnp.cumsum(hist, axis=2)                  # stats of bins <= b
    total = left[:, :, -1:, :]                       # (n_nodes, n, 1, S)
    right = total - left

    def impurity(s):
        if criterion == "gini":
            w = jnp.sum(s, axis=-1)
            p = s / jnp.maximum(w[..., None], 1e-12)
            return w * (1.0 - jnp.sum(p * p, axis=-1))
        w, wy, wy2 = s[..., 0], s[..., 1], s[..., 2]
        return wy2 - wy * wy / jnp.maximum(w, 1e-12)  # w * variance

    parent = impurity(total)                          # (n_nodes, n, 1)
    gain = parent - impurity(left) - impurity(right)  # (n_nodes, n, N_BINS)
    # last bin puts everything left — not a real split
    gain = gain.at[:, :, -1].set(-jnp.inf)
    wl = left[..., 0] if criterion == "mse" else jnp.sum(left, axis=-1)
    wr = right[..., 0] if criterion == "mse" else jnp.sum(right, axis=-1)
    gain = jnp.where((wl > 0) & (wr > 0), gain, -jnp.inf)
    return gain, total[:, 0, 0, :]                    # per-node totals (f=0)


def _mask_features(gain, key, try_features):
    """Restrict each node's search to a random feature subset (per node)."""
    n_nodes, n, _ = gain.shape
    if try_features is None or try_features >= n:
        return gain
    score = jax.random.uniform(key, (n_nodes, n))
    kth = lax.top_k(score, try_features)[0][:, -1]
    allowed = score >= kth[:, None]
    return jnp.where(allowed[:, :, None], gain, -jnp.inf)


def _level_step(node, bx, w, stats, key, n_nodes, try_features, min_gain,
                criterion, n_bins, hist="xla"):
    """Grow one level of one tree. Returns (feat, thr_bin, is_split, new_node,
    node_totals)."""
    hist = _node_histogram(node, bx, w, stats, n_nodes, n_bins, hist=hist)
    gain, totals = _gain_and_split(hist, criterion)
    gain = _mask_features(gain, key, try_features)
    flat = gain.reshape(n_nodes, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feat = (best // n_bins).astype(jnp.int32)
    tbin = (best % n_bins).astype(jnp.int32)
    is_split = best_gain > min_gain
    # pass-through for non-splitting nodes: everything goes left
    feat = jnp.where(is_split, feat, 0)
    tbin = jnp.where(is_split, tbin, n_bins - 1)
    # route samples: right iff bin(x_f) > threshold bin
    f_sel = feat[node]                                # (m,)
    b_sel = tbin[node]
    x_bin = jnp.take_along_axis(bx, f_sel[:, None], axis=1)[:, 0]
    go_right = (x_bin > b_sel) & is_split[node]
    new_node = node * 2 + go_right.astype(jnp.int32)
    return feat, tbin, is_split, new_node, totals


# one jitted step per (level-shape, config); vmapped over the whole forest.
# `node` (the (T, m_pad) per-sample node assignment) is DONATED: it aliases
# the returned new_node, so level growth updates the forest's largest
# carried array in place instead of double-buffering it.  The loop rebinds
# `node` to the output each level and never touches the old buffer (snapshot
# fetches read the NEW node, blocking, before the next level dispatches).
@partial(_pjit, static_argnames=("n_nodes", "try_features", "criterion",
                                 "n_bins", "hist"),
         donate_argnames=("node",), name="forest_level")
def _forest_level(node, bx, w, stats, keys, n_nodes, try_features,
                  min_gain, criterion, n_bins, hist="xla"):
    step = partial(_level_step, n_nodes=n_nodes, try_features=try_features,
                   min_gain=min_gain, criterion=criterion, n_bins=n_bins,
                   hist=hist)
    feat, tbin, is_split, new_node, totals = \
        jax.vmap(step, in_axes=(0, None, 0, None, 0))(
            node, bx, w, stats, keys)
    # fused health vector — same program, zero extra dispatches.  The
    # per-node stat totals are where a poisoned weight/stat carry first
    # shows up as NaN (feat/tbin/node are integral and cannot hold one).
    hvec = _health.health_vec(carries=(totals, w))
    return feat, tbin, is_split, new_node, totals, hvec


@partial(_pjit, static_argnames=("n_leaves",), name="leaf_stats")
def _leaf_stats(node, w, stats, n_leaves):
    """Final-level per-leaf stat sums: (T, n_leaves, S), plus the fused
    health vector over them (the forest's terminal numeric state — a NaN
    here is what would silently poison every prediction)."""
    def one(nd, wt):
        out = jnp.zeros((n_leaves, stats.shape[1]), jnp.float32)
        return out.at[nd].add(wt[:, None] * stats)
    leaves = jax.vmap(one)(node, w)
    return leaves, _health.health_vec(carries=(leaves,))


def _forest_apply_core(qp, q_shape, edges, feats, tbins, depth):
    """Leaf index of every query row in every tree: (T, mq_pad).  Plain
    traced body — shared by the jitted `_forest_apply`, the score
    kernels, and the fused predict nodes in `forest.py`."""
    bq = _bin_data(qp, q_shape, edges)                # (mq_pad, n)

    def one_tree(feat_l, tbin_l):
        node = jnp.zeros(bq.shape[0], jnp.int32)
        for lvl in range(depth):
            f = feat_l[lvl][node]
            b = tbin_l[lvl][node]
            x_bin = jnp.take_along_axis(bq, f[:, None], axis=1)[:, 0]
            node = node * 2 + (x_bin > b).astype(jnp.int32)
        return node

    return jax.vmap(one_tree)(feats, tbins)


@partial(_pjit, static_argnames=("depth", "q_shape"), name="forest_apply")
def _forest_apply(qp, q_shape, edges, feats, tbins, depth):
    return _forest_apply_core(qp, q_shape, edges, feats, tbins, depth)


# ---------------------------------------------------------------------------
# host-side tree builder shared by the estimators
# ---------------------------------------------------------------------------

def _pack_levels(levels, depth):
    """Traced pad+stack of the ragged per-level (T, 2^lvl) arrays — call
    INSIDE a jitted kernel only, where it fuses into the one program (see
    _grow_forest on why an eager pack is a deadlock hazard)."""
    wide = 2 ** (depth - 1)
    return jnp.stack([jnp.pad(a, ((0, 0), (0, wide - a.shape[1])))
                      for a in levels], axis=1)


class _BaseTreeEnsemble(BaseEstimator):
    """Shared fit/apply machinery; subclasses set `_criterion` and predictions."""

    _criterion = "gini"
    _private_fitted_attrs = ("_edges", "_feats", "_tbins", "_depth", "_leaves")

    def _effective_depth(self, m):
        d = self.max_depth
        if d is None or np.isinf(d):
            d = MAX_DEPTH_CAP
        elif d > MAX_DEPTH_CAP:
            import warnings
            warnings.warn(
                f"max_depth={d} exceeds the depth cap {MAX_DEPTH_CAP}: tree "
                f"node arrays are heap-shaped (2^depth is a compiled XLA "
                f"shape), so growth is capped at {MAX_DEPTH_CAP} levels — "
                "unlike the reference's data-bounded recursion. Deep "
                "fine-structure beyond the cap will not be modelled.",
                UserWarning, stacklevel=3)
        return int(max(1, min(d, MAX_DEPTH_CAP, int(np.ceil(np.log2(max(m, 2)))))))

    def _n_bins(self):
        nb = getattr(self, "n_bins", None)   # None: pre-n_bins snapshot load
        nb = N_BINS if nb is None else int(nb)
        if not 2 <= nb <= 1024:
            raise ValueError(f"n_bins must be in [2, 1024], got {nb}")
        return nb

    def _try_features_count(self, n):
        tf = getattr(self, "try_features", None)
        if tf in (None, "none"):
            return None
        if tf == "sqrt":
            return max(1, int(np.sqrt(n)))
        if tf == "third":
            return max(1, n // 3)
        return max(1, int(tf))

    def _grow_forest(self, x: Array, stats_host, n_trees, bootstrap,
                     checkpoint=None, health=None):
        """Dispatch the whole forest growth as device programs — no host
        read (the async-fit half; `_adopt_forest` materialises attrs).

        With ``checkpoint`` the grown-so-far state (node assignment,
        bootstrap weights, per-level splits, seed, level counter)
        snapshots every `every` LEVELS — trees grow level-synchronously,
        so a level boundary is the natural resumable point (SURVEY §6);
        the PRNG key chain is re-derived from the stored seed so a resumed
        growth is bit-identical to the uninterrupted one.  Checkpointed
        growth reads state to host between chunks (only then)."""
        m, n = x.shape
        depth = self._effective_depth(m)
        fp = digest = None
        if checkpoint is not None:
            from dislib_tpu.utils.checkpoint import (data_digest,
                                                     validate_snapshot)
            tf = self._try_features_count(n)
            rs = self.random_state
            # every knob the grown state depends on is fingerprinted —
            # resuming with a changed seed or feature-sampling width must
            # refuse, not grow a hybrid forest (round-4 review)
            fp = np.asarray([m, n, n_trees, depth, int(bootstrap),
                             float(("gini", "mse").index(self._criterion)),
                             -1.0 if tf is None else float(tf),
                             -1.0 if rs is None else float(rs),
                             float(self._n_bins())], np.float64)
            digest = data_digest(x._data, stats=stats_host)

        n_bins = self._n_bins()
        try_features = self._try_features_count(n)
        # histogram schedule: resolved ONCE here (the fit boundary — the
        # spmm/summa routing precedent, so a DSLIB_OVERLAP flip retraces
        # and the run is `hist:<sched>` counter-observable).  "pallas"
        # needs the hist-specific probe on top of the router's: a Mosaic
        # rejection of THIS kernel's shapes degrades to the XLA scatter.
        from dislib_tpu.ops import overlap as _ov
        from dislib_tpu.ops import pallas_kernels as _pk
        hist_sched = "pallas" if (_ov.resolve(None) == "pallas"
                                  and _pk.hist_available()) else "xla"
        _prof.count_schedule("hist", hist_sched)
        box = {"feats": [], "tbins": [], "x": x}

        def _stage():
            # everything derived from the data layout: binned data, pad
            # width, validity mask, per-sample stats.  Re-run by the
            # elastic rebind after a mesh change — the bins re-derive
            # from the re-laid-out x (the quantile edges depend only on
            # the VALID rows, so they are mesh-independent values on a
            # mesh-dependent canvas)
            xd = box["x"]._data
            mp = xd.shape[0]
            box["edges"] = _quantile_bins(xd, (m, n), n_bins)
            box["bx"] = _bin_data(xd, (m, n), box["edges"])
            box["mp"] = mp
            box["valid"] = (np.arange(mp) < m).astype(
                px.compute_dtype(px.FLOAT32))
            sh = np.asarray(stats_host)
            if sh.shape[0] != mp:       # host re-pad: pad rows carry w=0
                out = np.zeros((mp, sh.shape[1]), sh.dtype)
                out[: min(mp, sh.shape[0])] = sh[:mp]
                sh = out
            box["stats"] = jnp.asarray(sh)            # (mp, S)

        _stage()
        _data_hook = _fitloop.data_rebind(box)

        def rebind(mesh):
            _data_hook(mesh)            # force chains / re-canonicalize x
            if mesh is not None:
                _stage()

        loop = _fitloop.ChunkedFitLoop(
            "forest", checkpoint=checkpoint, health=health,
            max_iter=depth, chunk_iters=1,
            save_every=checkpoint.every if checkpoint is not None else 1,
            # the fused per-level health vector is read at snapshot
            # boundaries only (one sync per chunk, same cadence as the
            # snapshot's own blocking fetches); unchecked growth defers to
            # the adoption-time check
            check_on="save",
            # growth snapshots only resumable mid-points, never the final
            # level (leaves are derived after the loop)
            save_final=False,
            carry_names=("node_totals", "w"), elastic=rebind)

        def _keys_for(seed, lvl):
            # replay the PRNG key chain to `lvl` — a resumed or
            # rolled-back growth stays bit-identical
            key = jax.random.PRNGKey(int(seed))
            k_boot, key = jax.random.split(key)
            for _ in range(lvl):
                key, _ = jax.random.split(key)
            return k_boot, key

        def init(rem):
            if "seed" not in box:       # chosen once; rollbacks replay it
                box["seed"] = self.random_state \
                    if self.random_state is not None \
                    else np.random.randint(0, 2**31 - 1)
            k_boot, box["key"] = _keys_for(box["seed"], 0)
            box["feats"], box["tbins"] = [], []
            mp = box["mp"]
            if bootstrap:
                w = jax.random.poisson(k_boot, 1.0, (n_trees, mp)).astype(
                    px.compute_dtype(px.FLOAT32))
            else:
                w = jnp.ones((n_trees, mp), jnp.float32)
            w = w * jnp.asarray(box["valid"])[None, :]
            if rem.attempt:             # from-scratch rollback perturbs w
                w = jnp.asarray(rem.perturb(_fetch(w)))
            return _fitloop.LoopState(
                (w,), extra=jnp.zeros((n_trees, mp), jnp.int32))

        def restore(snap, rem):
            if "fp" in snap and np.size(snap["fp"]) == len(fp) - 1:
                # pre-n_bins forest snapshot (8-knob fp): the grown state
                # depends on a knob the old fp never recorded
                raise ValueError(
                    "checkpoint was written by a different library "
                    "version (forest fingerprint predates n_bins) — "
                    "delete the snapshot file to restart the fit")
            validate_snapshot(snap, fp, digest)
            box["seed"] = int(snap["seed"])
            lvl = int(snap["lvl"])
            _, box["key"] = _keys_for(box["seed"], lvl)
            # node assignment and bootstrap weights are per-(padded-)sample:
            # re-pad them for THIS mesh's quantum so an 8-device snapshot
            # resumes on a 4-device (or 2-D) mesh — pad columns carry w=0,
            # so zero-filling them is exact (elastic resume)
            node = jnp.asarray(_repad_rows(snap["node"], m, box["mp"],
                                           axis=1))
            w = jnp.asarray(rem.perturb(_repad_rows(snap["w"], m,
                                                    box["mp"], axis=1)))
            box["feats"] = [jnp.asarray(snap[f"feats_{i}"])
                            for i in range(lvl)]
            box["tbins"] = [jnp.asarray(snap[f"tbins_{i}"])
                            for i in range(lvl)]
            return _fitloop.LoopState((w,), it=lvl, extra=node)

        def step(st, chunk):
            box["key"], k_lvl = jax.random.split(box["key"])
            keys = jax.random.split(k_lvl, n_trees)
            (w,) = st.carries
            feat, tbin, is_split, node, _, hvec = _forest_level(
                st.extra, box["bx"], w, box["stats"], keys, 2 ** st.it,
                try_features, 0.0, self._criterion, n_bins,
                hist=hist_sched)
            box["feats"].append(feat)
            box["tbins"].append(tbin)
            nxt = st.it + 1
            return _fitloop.ChunkOutcome(
                _fitloop.LoopState((w,), nxt, nxt == depth, extra=node),
                hvec=hvec)

        def snapshot(st):
            # node is donated to the next level's kernel — its copy must
            # land on host before that dispatch (blocking fetch); only the
            # checksum+file write moves to the snapshot worker
            state = {"lvl": st.it, "seed": box["seed"], "fp": fp,
                     "digest": digest, "node": _fetch(st.extra),
                     "w": _fetch(st.carries[0])}
            # the per-level feats/tbins drain through the shared host-loop
            # pipeline: level i's blocking fetch runs under level i+1's
            # device→host DMA (db/seq bit-equal by construction, routed +
            # counter-observable like every overlap site)
            sched = _ov.resolve()
            _prof.count_schedule("forest_snapshot", sched)
            pairs = list(zip(box["feats"], box["tbins"]))

            def issue(i):
                for buf in pairs[i]:
                    if hasattr(buf, "copy_to_host_async"):
                        buf.copy_to_host_async()
                return pairs[i]

            def drain(i, pair):
                state[f"feats_{i}"] = _fetch(pair[0])
                state[f"tbins_{i}"] = _fetch(pair[1])

            _ov.host_pipeline(len(pairs), issue, drain,
                              overlap=_ov.overlapped(sched))
            return state

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        self.fit_info_ = loop.info
        feats, tbins = box["feats"], box["tbins"]
        leaves, leaf_hvec = _leaf_stats(st.extra, st.carries[0],
                                        box["stats"], 2 ** depth)
        # feats/tbins stay as the ragged per-level device arrays: packing
        # here would dispatch eager multi-device pad/stack programs while
        # the level producers are still in flight — on a thread-starved
        # XLA:CPU pool their parked rendezvous participants can starve the
        # producers into a true deadlock (observed round 3).  The pack
        # happens on host at adoption, or traced INSIDE the score kernels.
        # `hvec` rides along so the adoption step (the first host
        # materialisation) can refuse a non-finite forest — the async
        # dispatch-only contract of this function is preserved.
        return {"edges": box["edges"], "feats": tuple(feats),
                "tbins": tuple(tbins),
                "depth": depth, "leaves": leaves, "n_features": n,
                "hvec": leaf_hvec, "guard": loop.guard}

    def _adopt_forest(self, grown):
        """Materialise fitted attributes from a `_grow_forest` handle.
        The ragged per-level (T, 2^lvl) arrays pad+stack to (T, depth,
        2^(depth-1)) in host NumPy — tiny arrays, and no extra device
        programs — so predict calls are a single gather-walk jit.

        Adoption is the first host materialisation of the grown forest,
        so the fused leaf health vector is judged here: a non-finite
        forest raises a typed ``NumericalDivergence`` instead of silently
        serving NaN predictions (rollback is no longer possible at this
        point — the checkpointed growth loop already healed what it
        could)."""
        hvec = grown.get("hvec")
        if hvec is not None:
            g = grown.get("guard") or _health.guard("forest")
            v = g.check(hvec, carry_names=("leaves",),
                        carry_shapes=(np.shape(grown["leaves"]),))
            if not v.ok:
                raise _health.NumericalDivergence(
                    f"forest: health guard {v.guard!r} tripped at adoption "
                    f"— the grown forest is not numerically usable "
                    f"(detail: {v.detail})",
                    estimator="forest", guard=v.guard, detail=v.detail)
        wide = 2 ** (grown["depth"] - 1)

        def _pack(levels):
            # adoption's per-level reads pipeline like the snapshot loop:
            # level i's host landing overlaps level i+1's device→host DMA
            from dislib_tpu.ops import overlap as _ov
            sched = _ov.resolve()
            _prof.count_schedule("forest_adopt", sched)

            def issue(i):
                if hasattr(levels[i], "copy_to_host_async"):
                    levels[i].copy_to_host_async()
                return levels[i]

            host = _ov.host_pipeline(
                len(levels), issue,
                lambda i, a: np.asarray(jax.device_get(a)),
                overlap=_ov.overlapped(sched))
            return np.stack([np.pad(a, ((0, 0), (0, wide - a.shape[1])))
                             for a in host], axis=1)

        self._edges = grown["edges"]
        self._feats = _pack(grown["feats"])
        self._tbins = _pack(grown["tbins"])
        self._depth = grown["depth"]
        self._leaves = grown["leaves"]                 # (T, 2^depth, S)
        self.n_features_ = grown["n_features"]
        return self

    def fit(self, x: Array, y: Array, checkpoint=None, health=None):
        """Shared fit = the async protocol run to completion (one recipe —
        sync and async fits cannot diverge).  ``checkpoint``: see
        `_grow_forest` (per-level snapshots + resume); ``health``: see
        `_grow_forest` (per-chunk fused guards + rollback)."""
        self._fit_finalize(self._fit_async(x, y, checkpoint=checkpoint,
                                           health=health))
        return self

    # async trial protocol (SURVEY §4.5): growth is read-free device
    # dispatch; the handle is the grown-forest dict.  Label/target encoding
    # reads the INPUT y (prep, not fit results) at dispatch time, cached
    # per (y, padding) so a search encodes each fold once, not once per
    # candidate.
    def _fit_async(self, x, y=None, checkpoint=None, health=None):
        if y is None:
            raise ValueError(f"{type(self).__name__} requires y")
        stats = self._encode_stats(x, y)
        n_trees, bootstrap = self._fit_spec()
        return self._grow_forest(x, stats, n_trees, bootstrap,
                                 checkpoint=checkpoint, health=health)

    def _fit_finalize(self, state):
        if state is None:
            return
        self._adopt_forest(state)

    def _apply(self, x: Array):
        return _forest_apply(x._data, x.shape, jnp.asarray(self._edges),
                             jnp.asarray(self._feats), jnp.asarray(self._tbins),
                             self._depth)                   # (T, mq_pad)

    def _check_fitted(self):
        if not hasattr(self, "_leaves"):
            raise RuntimeError(f"{type(self).__name__} is not fitted")
