from dislib_tpu.trees.forest import (
    RandomForestClassifier, RandomForestRegressor,
    DecisionTreeClassifier, DecisionTreeRegressor,
)

__all__ = [
    "RandomForestClassifier", "RandomForestRegressor",
    "DecisionTreeClassifier", "DecisionTreeRegressor",
]
