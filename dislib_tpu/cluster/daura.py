"""Daura / GROMOS conformational clustering (reference:
`dislib/cluster/daura` — pairwise-RMSD-count tasks + an iterative "extract
the max-neighbor medoid" greedy outer loop; SURVEY.md §3.3).

TPU-native redesign: the reference distributes the *neighbor counting* (one
task per block pair) and keeps the greedy loop on the master, syncing counts
every round.  Here the full pairwise RMSD adjacency is one distance GEMM and
the entire greedy loop — count active neighbors, argmax, peel the medoid's
neighborhood, repeat — runs on device inside a single `lax.while_loop` with
no host round-trips: each round is a masked reduce + argmax + row-gather on
the resident adjacency matrix.

Frames are rows of the ds-array, ``3·n_atoms`` coordinates per row (the
layout `load_mdcrd_file` produces).  RMSD(i, j) = √(‖xᵢ − xⱼ‖² / n_atoms),
without superposition — matching the reference, which clusters pre-aligned
trajectories.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad, \
    ensure_canonical as _ensure_canonical
from dislib_tpu.ops import distances_sq
from dislib_tpu.ops.base import precise
from dislib_tpu.ops import tiled as _tiled
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops.ring import ring_auto, ring_neigh_count_min
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health

# padded frame counts above this stream the RMSD adjacency in tiles
# (module-level so tests can force the path)
_DENSE_MAX = 16384

# ring-distribute the streamed passes over the mesh 'rows' axis (None=auto:
# >1 row shard and past _DENSE_MAX; module-level so tests can force it)
_RING = None


class Daura(BaseEstimator):
    """GROMOS clustering of MD trajectory frames.

    Parameters
    ----------
    cutoff : float — RMSD threshold for two frames to be neighbors.

    Attributes
    ----------
    clusters_ : list of ndarray — one per cluster, frame indices with the
        medoid first; ordered by extraction (largest neighborhoods first).
    labels_ : ndarray (n_frames,) int — cluster id per frame.
    """

    def __init__(self, cutoff=1.0):
        self.cutoff = cutoff

    def fit(self, x: Array, y=None, checkpoint=None, health=None):
        """Fit.  With ``checkpoint=FitCheckpoint(path, every=k)`` the greedy
        state (active mask, labels, medoids, cluster counter) snapshots
        every k extracted clusters, on whichever streamed tier the plain
        fit would pick (ring on a multi-row mesh, tiled otherwise); a
        re-run resumes the extraction and lands on the uninterrupted
        run's clustering (the greedy loop is deterministic in its carried
        state — SURVEY §6).

        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`.
        The greedy state is integral, so the fused guard watches the
        INPUT frames: a non-finite coordinate silently fails every RMSD
        cutoff comparison — the guard raises a typed
        ``NumericalDivergence`` instead (quarantine the frames at
        ingest).  The chunk watchdog covers hung extraction passes."""
        if x.shape[1] % 3 != 0:
            raise ValueError("Daura expects rows of 3*n_atoms coordinates")
        n_atoms = x.shape[1] // 3
        mesh = _mesh.get_mesh()
        # ring-tier shard_map splits rows over the mesh — an input built
        # under another mesh re-lays out on device (never a host hop)
        x = _ensure_canonical(x)
        if checkpoint is not None:
            labels, medoids = self._fit_checkpointed(x, n_atoms, checkpoint,
                                                     mesh, health)
        else:
            def step(st, chunk):
                if ring_auto(_RING, mesh, x._data.shape[0] > _DENSE_MAX):
                    # rotate/compute schedule: resolved at this host
                    # boundary (DSLIB_OVERLAP flips retrace via the static)
                    sched = _ov.resolve()
                    _prof.count_schedule("ring_neigh", sched)
                    labels, medoids, hvec = _daura_fit_ring(
                        x._data, x.shape, float(self.cutoff), n_atoms, mesh,
                        overlap=sched)
                elif x._data.shape[0] <= _DENSE_MAX:
                    labels, medoids, hvec = _daura_fit(
                        x._data, x.shape, float(self.cutoff), n_atoms)
                else:
                    # single-device tiled tier: no collective to overlap,
                    # but the pallas route still picks the inner kernel
                    sched = _ov.resolve()
                    _prof.count_schedule("tiled_neigh", sched)
                    labels, medoids, hvec = _daura_fit_tiled(
                        x._data, x.shape, float(self.cutoff), n_atoms,
                        _tiled.TILE, use_pallas=(sched == "pallas"))
                return _fitloop.ChunkOutcome(
                    _fitloop.LoopState((), 0, True, extra=(labels, medoids)),
                    hvec=hvec)      # input faults: typed raise via the loop

            loop = _fitloop.ChunkedFitLoop("daura", health=health)
            st = loop.run(init=lambda rem: _fitloop.LoopState(()), step=step)
            self.fit_info_ = loop.info
            labels, medoids = st.extra
        labels = np.asarray(jax.device_get(labels))[: x.shape[0]]
        medoids = np.asarray(jax.device_get(medoids))
        self.labels_ = labels.astype(np.int64)
        clusters = []
        for cid in range(int(labels.max()) + 1 if labels.size else 0):
            members = np.nonzero(labels == cid)[0]
            med = int(medoids[cid])
            clusters.append(np.concatenate(([med], members[members != med])))
        self.clusters_ = clusters
        return self

    def fit_predict(self, x: Array, y=None) -> Array:
        self.fit(x)
        lab = jnp.asarray(self.labels_.astype(np.int32)[:, None])
        return Array._from_logical_padded(_repad(lab, (x.shape[0], 1)),
                                          (x.shape[0], 1))

    def _fit_checkpointed(self, x: Array, n_atoms, checkpoint, mesh,
                          health=None):
        """Chunked fit: `every` cluster extractions per dispatch, the
        greedy state snapshotted between chunks.  The ring tier is picked
        by the same policy as the plain fit (scale-out + fault tolerance
        compose).  The greedy state is all frame ids and −1/False fills —
        pad-width independent — so the pad width is NOT fingerprinted
        (round 16): a snapshot resumes on any mesh/tier and the elastic
        rebind re-stages the extraction closure for the new topology."""
        from dislib_tpu.utils.checkpoint import data_digest, validate_snapshot
        cutoff = float(self.cutoff)
        m = x.shape[0]
        box = {"x": x}

        def _stage(cur_mesh):
            xd = box["x"]._data
            if ring_auto(_RING, cur_mesh, xd.shape[0] > _DENSE_MAX):
                mp = xd.shape[0]
                sched = _ov.resolve()
                _prof.count_schedule("ring_neigh", sched)

                def extract(active, labels, medoids, cid):
                    return _daura_extract_ring(
                        xd, cutoff, n_atoms, cur_mesh, active, labels,
                        medoids, cid, max_new=checkpoint.every,
                        overlap=sched)
            else:
                # tiles-padded row count, computed arithmetically
                # (pad_to_tiles' own formula) — no eager padded copy
                mp = -(-xd.shape[0] // _tiled.TILE) * _tiled.TILE
                # single-device tiled tier: the pallas route picks the
                # inner kernel (no collective to overlap)
                sched = _ov.resolve()
                _prof.count_schedule("tiled_neigh", sched)

                def extract(active, labels, medoids, cid):
                    return _daura_extract_tiled(
                        xd, x.shape, cutoff, n_atoms, _tiled.TILE, active,
                        labels, medoids, cid, max_new=checkpoint.every,
                        use_pallas=(sched == "pallas"))
            box.update(mp=mp, extract=extract)

        _stage(mesh)
        _data_hook = _fitloop.data_rebind(box)

        def rebind(new_mesh):
            _data_hook(new_mesh)        # force chains / re-canonicalize x
            if new_mesh is not None:
                _stage(new_mesh)

        fp = np.asarray([x.shape[0], x.shape[1], cutoff], np.float64)
        digest = data_digest(x._data)
        loop = _fitloop.ChunkedFitLoop("daura", checkpoint=checkpoint,
                                       health=health, elastic=rebind)

        def init(rem):
            mp = box["mp"]
            return _fitloop.LoopState(
                (jnp.full((mp,), -1, jnp.int32),),
                extra=(jnp.arange(mp, dtype=jnp.int32) < m,
                       jnp.full((mp,), -1, jnp.int32), jnp.int32(0)))

        def restore(snap, rem):
            validate_snapshot(snap, fp, digest)
            mp = box["mp"]
            # the greedy state stores frame ids (< m) with −1 fills and a
            # False active mask on pads — crop to the logical rows and
            # re-pad for THIS pad width, exact under any resize
            lab = np.pad(np.asarray(snap["labels"])[:m], (0, mp - m),
                         constant_values=-1)
            act = np.pad(np.asarray(snap["active"])[:m], (0, mp - m))
            med = np.pad(np.asarray(snap["medoids"])[:m], (0, mp - m),
                         constant_values=-1)
            return _fitloop.LoopState(
                (jnp.asarray(lab),),
                extra=(jnp.asarray(act), jnp.asarray(med),
                       jnp.int32(int(snap["cid"]))))

        def step(st, chunk):
            (labels,) = st.carries
            active, medoids, cid = st.extra
            active, labels, medoids, cid, hvec = box["extract"](
                active, labels, medoids, cid)
            # state deferred: the watchdogged hvec read (the chunk force
            # point) precedes the active-set convergence fetch
            return _fitloop.ChunkOutcome(
                lambda: _fitloop.LoopState(
                    (labels,), st.it + 1,
                    not bool(_fetch(jnp.any(active))),
                    extra=(active, medoids, cid)),
                hvec=hvec)

        def snapshot(st):
            # blocking fetches (the round's own sync), async file write —
            # the checksum+atomic rename overlaps the next extract round
            active, medoids, cid = st.extra
            return {"active": _fetch(active), "labels": _fetch(st.carries[0]),
                    "medoids": _fetch(medoids), "cid": int(_fetch(cid)),
                    "fp": fp, "digest": digest}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        self.fit_info_ = loop.info
        return st.carries[0], st.extra[1]


@partial(jax.jit, static_argnames=("shape", "n_atoms"))
@precise
def _daura_fit(xp, shape, cutoff, n_atoms):
    m, n = shape
    xv = xp[:, :n]
    mp = xv.shape[0]

    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    rmsd2 = distances_sq(xv, xv) / n_atoms
    adj = (rmsd2 <= cutoff * cutoff) & valid[:, None] & valid[None, :]
    # structural self-loops: every frame is its own neighbor, so each round
    # removes ≥1 frame and the loop terminates regardless of fp rounding
    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)
    adj = adj | (jnp.eye(mp, dtype=jnp.bool_) & valid[:, None])

    def body(carry):
        active, labels, medoids, cid = carry
        counts = jnp.sum(adj & active[None, :], axis=1)   # active-neighbor counts
        counts = jnp.where(active, counts, -1)
        medoid = jnp.argmax(counts).astype(jnp.int32)
        members = (adj[medoid] | (ids == medoid)) & active
        labels = jnp.where(members, cid, labels)
        medoids = medoids.at[cid].set(medoid)
        return active & ~members, labels, medoids, cid + 1

    def cond(carry):
        return jnp.any(carry[0])

    labels0 = jnp.full((mp,), -1, jnp.int32)
    medoids0 = jnp.full((mp,), -1, jnp.int32)
    active0 = valid
    _, labels, medoids, _ = lax.while_loop(
        cond, body, (active0, labels0, medoids0, jnp.int32(0)))
    # fused input guard — non-finite frame coordinates silently fail every
    # cutoff comparison, so they must trip, not pass through
    hvec = _health.health_vec(inputs=(jnp.where(valid[:, None], xv, 0.0),))
    return labels, medoids, hvec


@partial(jax.jit, static_argnames=("shape", "n_atoms", "tile", "max_new",
                                   "use_pallas"))
@precise
def _daura_extract_tiled(xp, shape, cutoff, n_atoms, tile, active, labels,
                         medoids, cid, max_new, use_pallas=False):
    """Extract ≤ max_new clusters from the current greedy state (tiled
    passes).  Each extraction is one cluster = one pass; bounding the count
    is the mid-fit checkpoint boundary (SURVEY §6): the carried
    (active, labels, medoids, cid) state between chunks IS the resumable
    state, and greedy extraction is deterministic given it."""
    m, n = shape
    cut2 = cutoff * cutoff * n_atoms          # rmsd² ≤ cutoff² ⇔ d² ≤ cut2
    xv, _ = _tiled.pad_to_tiles(xp[:, :n], tile)
    mp = xv.shape[0]
    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)

    def body(carry):
        active_, labels_, medoids_, cid_, k = carry
        counts, _ = _tiled.neigh_count_min(xv, cut2, ids, active_,
                                           jnp.int32(mp), tile,
                                           use_pallas=use_pallas)
        counts = jnp.where(active_, counts, -1)
        medoid = jnp.argmax(counts).astype(jnp.int32)
        mrow = distances_sq(xv[medoid][None, :], xv)[0]
        members = ((mrow <= cut2) | (ids == medoid)) & active_
        labels_ = jnp.where(members, cid_, labels_)
        medoids_ = medoids_.at[cid_].set(medoid)
        return active_ & ~members, labels_, medoids_, cid_ + 1, k + 1

    def cond(carry):
        return jnp.any(carry[0]) & (carry[4] < max_new)

    active, labels, medoids, cid, _ = lax.while_loop(
        cond, body, (active, labels, medoids, cid, jnp.int32(0)))
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    hvec = _health.health_vec(inputs=(jnp.where(valid[:, None], xv, 0.0),))
    return active, labels, medoids, cid, hvec


def _daura_fit_tiled(xp, shape, cutoff, n_atoms, tile, use_pallas=False):
    """Greedy GROMOS loop without the resident m×m adjacency: each round's
    active-neighbor counts are a streamed tile pass (`ops/tiled.py`), and
    the extracted medoid's neighborhood is one (1, m) distance row.  Trades
    one O(m²/tile²)-GEMM pass per extracted cluster for O(tile²) memory —
    the same memory-for-recompute trade the reference's block-pair count
    tasks made.  One unbounded call of the chunkable extraction kernel
    (the tiles-padded row count is arithmetic — padding happens inside
    the jitted kernel, never eagerly)."""
    m, n = shape
    mp = -(-xp.shape[0] // tile) * tile
    valid = jnp.arange(mp, dtype=jnp.int32) < m
    labels0 = jnp.full((mp,), -1, jnp.int32)
    medoids0 = jnp.full((mp,), -1, jnp.int32)
    _, labels, medoids, _, hvec = _daura_extract_tiled(
        xp, shape, cutoff, n_atoms, tile, valid, labels0, medoids0,
        jnp.int32(0), max_new=1 << 30, use_pallas=use_pallas)
    return labels, medoids, hvec


@partial(jax.jit, static_argnames=("n_atoms", "mesh", "max_new", "overlap"))
@precise
def _daura_extract_ring(xp, cutoff, n_atoms, mesh, active, labels,
                        medoids, cid, max_new, overlap="db"):
    """Ring-tier bounded extraction: ≤ max_new clusters from the current
    greedy state, active-neighbor counts ring-distributed over the mesh
    'rows' axis (ops/ring.py) — frames stay row-sharded, only the
    medoid's (1, m) distance row and the greedy control flow are global.
    The bound is the mid-fit checkpoint boundary, as in the tiled tier."""
    cut2 = jnp.asarray(cutoff * cutoff * n_atoms, xp.dtype)
    mp = xp.shape[0]
    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)

    def body(carry):
        active_, labels_, medoids_, cid_, k = carry
        counts, _ = ring_neigh_count_min(xp, cut2, ids, active_,
                                         jnp.int32(mp), mesh,
                                         overlap=overlap)
        counts = jnp.where(active_, counts, -1)
        medoid = jnp.argmax(counts).astype(jnp.int32)
        mrow = distances_sq(xp[medoid][None, :], xp)[0]
        members = ((mrow <= cut2) | (ids == medoid)) & active_
        labels_ = jnp.where(members, cid_, labels_)
        medoids_ = medoids_.at[cid_].set(medoid)
        return active_ & ~members, labels_, medoids_, cid_ + 1, k + 1

    active, labels, medoids, cid, _ = lax.while_loop(
        lambda c: jnp.any(c[0]) & (c[4] < max_new), body,
        (active, labels, medoids, cid, jnp.int32(0)))
    # pad rows/cols are zero under the pad-and-mask invariant, so the raw
    # sharded backing is safe to scan for non-finite input coordinates
    hvec = _health.health_vec(inputs=(xp,))
    return active, labels, medoids, cid, hvec


def _daura_fit_ring(xp, shape, cutoff, n_atoms, mesh, overlap="db"):
    """One unbounded call of the ring extraction kernel."""
    m, _ = shape
    mp = xp.shape[0]
    valid = jnp.arange(mp, dtype=jnp.int32) < m
    labels0 = jnp.full((mp,), -1, jnp.int32)
    medoids0 = jnp.full((mp,), -1, jnp.int32)
    _, labels, medoids, _, hvec = _daura_extract_ring(
        xp, cutoff, n_atoms, mesh, valid, labels0, medoids0,
        jnp.int32(0), max_new=1 << 30, overlap=overlap)
    return labels, medoids, hvec
