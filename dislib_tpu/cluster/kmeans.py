"""KMeans — the north-star estimator (reference: `dislib/cluster/kmeans` —
`_partial_sum` per row block, arity-tree `_merge`, per-iteration host sync;
SURVEY.md §3.3 and §4.2; BASELINE configs 1 and ★).

TPU-native redesign (the survey's §4.2 TPU mapping, verbatim):

- The whole Lloyd's iteration is ONE jitted step inside a `lax.while_loop`
  that runs ON DEVICE — the host syncs once per *fit*, not once per
  iteration.  The reference pays B task submissions + a tree of merge tasks
  + one worker→master sync every iteration; here an iteration is one fused
  XLA program over the row-sharded data.
- `_partial_sum`'s per-block (distances → argmin → per-cluster Σx/count)
  becomes: a (m, k) distance matrix via one GEMM (‖x‖² − 2x·cᵀ + ‖c‖²,
  MXU-bound), argmin, and the per-cluster sums as `onehotᵀ @ x` — another
  GEMM.  The arity-tree `_merge` is the row-axis partial-sum reduction XLA
  emits as a `psum` over ICI.  The `arity` knob is gone: reduction topology
  belongs to the compiler (SURVEY §6).
- Padded (zero) rows carry weight 0 so they never perturb sums or counts.
- A Pallas fused E-step kernel was built and benchmarked in round 2 (single
  pass over x per iteration vs the XLA path's two GEMM reads): 105-111
  iter/s across tile sizes 512-4096 vs 124 iter/s for this XLA path on the
  1M×100 k=10 north star (TPU v5e).  XLA's own fusion already wins, so the
  kernel was deleted (SURVEY §8: "Pallas only where XLA fusion MEASURABLY
  falls short").
"""

from __future__ import annotations

from functools import partial

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad, ensure_canonical, \
    fused_kernel
from dislib_tpu.data.sparse import SparseArray, _spmm
from dislib_tpu.ops import distances_sq as _distances_sq
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health
from dislib_tpu.utils.dlog import verbose_logger
from dislib_tpu.utils.profiling import profiled_jit as _pjit


class KMeans(BaseEstimator):
    """Lloyd's K-means.

    Parameters (reference parity; `arity` accepted and ignored — reduction
    topology is XLA's job now)
    ----------
    n_clusters : int, default 8
    init : 'random' or ndarray (n_clusters, n_features)
    max_iter : int, default 10
    tol : float, default 1e-4 — convergence on ‖Δcenters‖².
    arity : int — ignored (reference reduction-tree fan-in).
    random_state : int or None

    Attributes
    ----------
    centers_ : ndarray (n_clusters, n_features)
    n_iter_ : int
    inertia_ : float — within-cluster sum of squared distances.
    history_ : ndarray (n_iter_,) — per-iteration inertia (SURVEY §6
        observability row).
    """

    def __init__(self, n_clusters=8, init="random", max_iter=10, tol=1e-4,
                 arity=50, random_state=None, verbose=False,
                 fast_distance=None):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.arity = arity
        self.random_state = random_state
        self.verbose = verbose
        # E-step distance GEMM at backend-default (bf16 MXU) precision:
        # assignment-only speed/exactness knob — possible argmin flips for
        # near-tied boundary points (~‖x‖²/256 cross-term error).  None
        # reads DSLIB_KMEANS_FAST_DISTANCE (launch-script default).
        self.fast_distance = fast_distance

    def _fast(self) -> bool:
        if self.fast_distance is not None:
            return bool(self.fast_distance)
        return os.environ.get("DSLIB_KMEANS_FAST_DISTANCE", "0") == "1"

    # -- fitting -------------------------------------------------------------

    def _init_centers(self, x):
        k, n = self.n_clusters, x.shape[1]
        if isinstance(self.init, (np.ndarray, list)):
            c = np.asarray(self.init, dtype=np.float32)
            if c.shape != (k, n):
                raise ValueError(f"init centers must be {(k, n)}, got {c.shape}")
            return jnp.asarray(c)
        if self.init != "random":
            raise ValueError(f"unsupported init {self.init!r}")
        rng = np.random.RandomState(self.random_state)
        # sample k distinct rows — the reference inits from data rows too
        idx = rng.choice(x.shape[0], size=min(k, x.shape[0]), replace=False)
        if isinstance(x, SparseArray):
            # BCOO row gather: filter the host triplets for the k chosen
            # rows and scatter into a (k, n) dense block — O(nnz) filter +
            # O(k·n) result, never an O(k·m) selection operand (the
            # sharded-rows fit path fetches these same triplets anyway)
            sidx = np.sort(idx)
            ind = np.asarray(jax.device_get(x._bcoo.indices))
            val = np.asarray(jax.device_get(x._bcoo.data), np.float32)
            pos = np.searchsorted(sidx, ind[:, 0])
            pos = np.minimum(pos, len(sidx) - 1)
            hit = sidx[pos] == ind[:, 0]
            rows_np = np.zeros((len(sidx), n), np.float32)
            np.add.at(rows_np, (pos[hit], ind[hit, 1]), val[hit])
            rows = jnp.asarray(rows_np)
        else:
            rows = x[np.sort(idx), :]._data[: len(idx), : n]
        if len(idx) < k:  # fewer samples than clusters: top up with jitter
            extra = rows[rng.randint(0, len(idx), k - len(idx))] + 1e-3
            rows = jnp.concatenate([rows, extra], axis=0)
        return rows

    def fit(self, x: Array, y=None, checkpoint=None, health=None):
        """Fit on `x`.  With ``checkpoint=FitCheckpoint(path, every=k)`` the
        device loop runs in k-iteration chunks, snapshotting (centers,
        n_iter) after each; a re-run resumes from the snapshot (SURVEY §6
        checkpoint/resume — TPU preemption recovery).  The whole per-chunk
        resilience protocol — fused health vector at zero extra
        dispatches, watchdog, verdict-gated snapshot writes,
        rollback-to-last-good with the ``health`` policy's escalation
        ladder (dense fits offer the elastic mesh-shrink tier), preemption
        polling — is owned by :class:`~dislib_tpu.runtime.ChunkedFitLoop`;
        centers are host-side logical state, so snapshots restore onto a
        different mesh/device count unchanged (elastic resume)."""
        sparse_in = isinstance(x, SparseArray)
        box = {"x": x, "inertia": None}
        log = verbose_logger("kmeans", self.verbose)
        # data_rebind handles BOTH backings since round 14: dense arrays
        # re-canonicalize, sparse arrays reshard their panel buffers on
        # device — the elastic mesh-shrink tier no longer degrades for
        # sparse fits
        loop = _fitloop.ChunkedFitLoop(
            "kmeans", checkpoint=checkpoint, health=health,
            max_iter=self.max_iter, carry_names=("centers",),
            carry_shapes=((self.n_clusters, x.shape[1]),),
            snapshot_expect={"centers": (self.n_clusters, x.shape[1])},
            elastic=_fitloop.data_rebind(box))

        def init(rem):
            box["inertia"] = None
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(self._init_centers(box["x"]))),))

        def restore(snap, rem):
            # snapshot compatibility (centers shape) is declared via
            # snapshot_expect and judged by the rollback funnel
            centers = np.asarray(snap["centers"])
            # a faulted chunk's inertia must not leak into the fitted
            # attrs if the restored state exits the loop (converged
            # snapshot): None falls back to -score(x)
            box["inertia"] = None
            return _fitloop.LoopState((jnp.asarray(rem.perturb(centers)),),
                                      it=int(snap["n_iter"]),
                                      done=bool(snap.get("converged", False)))

        def step(st, chunk):
            (centers,) = st.carries
            if sparse_in:
                data, lrows, cols, rowsq = box["x"].sharded_rows()
                centers, n_done, inertia, shift, hist, hvec = \
                    _kmeans_fit_sparse_sharded(
                        data, lrows, cols, rowsq, centers, x.shape[0], chunk,
                        float(self.tol), _mesh.get_mesh())
            else:
                xd = box["x"]
                centers, n_done, inertia, shift, hist, hvec = _kmeans_fit(
                    xd._data, xd.shape, centers, chunk, float(self.tol),
                    fast=self._fast())

            def commit():
                # deferred: these scalar syncs run only AFTER the verdict,
                # so the watchdogged hvec read is the chunk's first force
                # point (and a faulted chunk never touches the box)
                box["inertia"] = inertia
                it = st.it + int(n_done)
                done = float(shift) < self.tol
                log.info("iter %d: inertia=%.6g shift=%.3g", it,
                         float(inertia), float(shift))
                return _fitloop.LoopState((centers,), it, done)

            return _fitloop.ChunkOutcome(
                commit, hvec=hvec,
                history=lambda: _fetch(hist)[: int(n_done)])

        def snapshot(st):
            # async offload: the device->host copy starts now and the file
            # write runs on the snapshot worker, both overlapping the next
            # chunk's compute (centers are never donated)
            return {"centers": _fetch(st.carries[0], blocking=False),
                    "n_iter": st.it, "converged": st.done}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        self.centers_ = np.asarray(jax.device_get(st.carries[0]))
        self.n_iter_ = st.it
        self.history_ = np.asarray(loop.history, dtype=np.float64)
        self.fit_info_ = loop.info
        # inertia is None only when resuming an already-finished fit
        self.inertia_ = float(box["inertia"]) \
            if box["inertia"] is not None else -self.score(box["x"])
        return self

    # async trial protocol (SURVEY §4.5): fit/score entirely on device, no
    # host read until GridSearchCV has dispatched every trial
    def _fit_async(self, x, y=None):
        if isinstance(x, SparseArray):
            return super()._fit_async(x, y)
        centers0 = self._init_centers(x)
        return _kmeans_fit(x._data, x.shape, centers0, self.max_iter,
                           float(self.tol), fast=self._fast())

    def _fit_finalize(self, state):
        if state is None:
            return
        centers, n_iter, inertia, _, hist, _ = state
        self.centers_ = np.asarray(jax.device_get(centers))
        self.n_iter_ = int(n_iter)
        self.inertia_ = float(inertia)
        self.history_ = np.asarray(
            jax.device_get(hist), dtype=np.float64)[: self.n_iter_]

    def _score_async(self, state, x, y=None):
        if state is None or isinstance(x, SparseArray):
            self._fit_finalize(state)
            return super()._score_async(state, x, y)
        return _kmeans_score(x._data, x.shape, state[0])

    def fit_predict(self, x: Array, y=None) -> Array:
        return self.fit(x).predict(x)

    def predict(self, x) -> Array:
        """Cluster index per row.  Dense inputs build a fusion-graph node
        (`data.array.fused_kernel`): a scaler → predict pipeline runs as
        ONE cached XLA dispatch end-to-end — the serving-layer hot path."""
        self._check_fitted()
        if isinstance(x, SparseArray):
            d = _sparse_distances(x._bcoo, x.row_norms_sq(),
                                  jnp.asarray(self.centers_))
            labels = jnp.argmin(d, axis=1).astype(jnp.int32)[:, None]
            return Array._from_logical_padded(_repad(labels, (x.shape[0], 1)),
                                              (x.shape[0], 1))
        # serve on the CURRENT mesh: an input built before an elastic
        # resize re-lands on device (never the host) — round 16
        x = ensure_canonical(x)
        (centers,) = self._predict_leaves(self.centers_)
        return fused_kernel(
            _kmeans_predict_kernel, (x.shape,), (x, centers),
            (x.shape[0], 1), jnp.int32, out_pshape=(x._pshape[0], 1))

    def score(self, x, y=None) -> float:
        """Negative inertia on x (sklearn convention)."""
        self._check_fitted()
        if isinstance(x, SparseArray):
            d = _sparse_distances(x._bcoo, x.row_norms_sq(),
                                  jnp.asarray(self.centers_))
            return -float(jnp.sum(jnp.min(d, axis=1)))
        return float(_kmeans_score(x._data, x.shape, jnp.asarray(self.centers_)))

    def _check_fitted(self):
        if not hasattr(self, "centers_"):
            raise RuntimeError("KMeans is not fitted")


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

@partial(_pjit, static_argnames=("shape", "max_iter", "fast"),
         name="kmeans_fit")
@precise
def _kmeans_fit(xp, shape, centers0, max_iter, tol, fast=False):
    m, n = shape
    xv = xp[:, :n]  # crop padded cols; padded rows stay (weighted 0)
    xv = lax.with_sharding_constraint(xv, _mesh.row_sharding())
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m).astype(xv.dtype)
    k = centers0.shape[0]
    # loop-invariant hoists: ‖x‖² is constant across iterations, and the
    # fast path stores x ONCE as bfloat16 so the per-iteration distance
    # GEMM reads 2 bytes/element instead of 4 (same values the MXU's own
    # input rounding would produce — only the HBM traffic changes).  The
    # center-update GEMM still reads the f32 copy, keeping centers exact.
    x_sq = jnp.sum(xv * xv, axis=1, keepdims=True)
    xd = xv.astype(jnp.bfloat16) if fast else xv

    def step(carry):
        centers, _, it, _, hist = carry
        cross = jnp.matmul(xd, centers.astype(xd.dtype).T,
                           precision="default" if fast else None,
                           preferred_element_type=xv.dtype)
        c_sq = jnp.sum(centers * centers, axis=1)
        d = jnp.maximum(x_sq - 2.0 * cross + c_sq[None, :], 0.0)
        labels = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(labels, k, dtype=xv.dtype) * w[:, None]
        sums = onehot.T @ xv                 # (k, n) — row-axis psum under SPMD
        counts = jnp.sum(onehot, axis=0)     # (k,)
        new_centers = jnp.where(counts[:, None] > 0,
                                sums / jnp.maximum(counts, 1.0)[:, None],
                                centers)
        shift = jnp.sum((new_centers - centers) ** 2)
        inertia = jnp.sum(jnp.min(d, axis=1) * w)
        return new_centers, shift, it + 1, inertia, hist.at[it].set(inertia)

    def cond(carry):
        _, shift, it, _, _ = carry
        return (it < max_iter) & (shift >= tol)

    init = (centers0, jnp.asarray(jnp.inf, xv.dtype), jnp.int32(0),
            jnp.asarray(0.0, xv.dtype), jnp.zeros((max_iter,), xv.dtype))
    centers, shift, n_iter, inertia, hist = lax.while_loop(cond, step, init)
    # fused health vector — same program, zero extra dispatches (inertia
    # is nonincreasing under exact Lloyd's, so `hist` is the monotone
    # signal; the guard's threshold is host-side policy)
    hvec = _health.health_vec(carries=(centers,), hist=hist, n_done=n_iter)
    return centers, n_iter, inertia, shift, hist, hvec


def _kmeans_predict_core(xp, shape, centers):
    m, n = shape
    xv = xp[:, :n]
    d = _distances_sq(xv, centers)
    # labels stay int32 (consistent with the kNN indices path — float32 is
    # exact only below 2^24)
    labels = jnp.argmin(d, axis=1).astype(jnp.int32)
    # zero out padded rows to keep the Array invariant
    valid = lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m
    labels = jnp.where(valid, labels, 0)
    return labels[:, None]


def _kmeans_predict_kernel(cfg, xp, centers):
    """`predict` as a fusion-node body (cfg = (logical shape,)) — the ONE
    E-step distance + argmin, riding whatever op chain feeds it."""
    return _kmeans_predict_core(xp, cfg[0], centers)


@partial(_pjit, static_argnames=("shape",), name="kmeans_predict")
@precise
def _kmeans_predict(xp, shape, centers):
    return _kmeans_predict_core(xp, shape, centers)


def _sparse_distances(bcoo, rowsq, centers):
    """Squared distances (m, k) with the cross-term as one spmm."""
    c_sq = jnp.sum(centers * centers, axis=1)
    cross = _spmm(bcoo, centers.T)
    return jnp.maximum(rowsq[:, None] - 2.0 * cross + c_sq[None, :], 0.0)


@partial(_pjit, static_argnames=("m", "max_iter", "mesh"),
         name="kmeans_fit_sparse")
def _kmeans_fit_sparse_sharded(data, lrows, cols, rowsq, centers0, m,
                               max_iter, tol, mesh):
    """Sparse-path Lloyd's on the row-sharded rectangular representation
    (`SparseArray.sharded_rows`): per iteration each shard computes its
    rows' distance cross-term shard-locally (gather centersᵀ at the entry
    columns, scale, segment-sum by local row), and the per-cluster (Σx,
    count) partials combine with ONE `psum` over the rows axis — the same
    communication structure as the dense `_kmeans_fit` (SURVEY §8 hard
    part 2: sharded spmm + psum, not a single-device BCOO)."""
    p = mesh.shape[_mesh.ROWS]
    m_local = rowsq.shape[1]
    k = centers0.shape[0]

    def shard_fn(d_s, lr_s, cc_s, rsq_s, c0):
        d_e, lr, cc, rsq = d_s[0], lr_s[0], cc_s[0], rsq_s[0]
        offset = lax.axis_index(_mesh.ROWS) * m_local
        valid = (offset + lax.broadcasted_iota(jnp.int32, (m_local,), 0)) < m

        def step(carry):
            centers, _, it, _, hist = carry
            c_sq = jnp.sum(centers * centers, axis=1)
            # cross = x_local @ centersᵀ, one gather + segment_sum
            contrib = centers.T[cc] * d_e[:, None]           # (nnz, k)
            cross = jax.ops.segment_sum(contrib, lr, num_segments=m_local)
            dist = jnp.maximum(rsq[:, None] - 2.0 * cross + c_sq[None, :],
                               0.0)
            labels = jnp.argmin(dist, axis=1)
            onehot = jax.nn.one_hot(labels, k, dtype=centers.dtype) \
                * valid[:, None].astype(centers.dtype)
            counts = lax.psum(jnp.sum(onehot, axis=0), _mesh.ROWS)
            # sums = xᵀ onehot: shard-local partial + psum
            contrib2 = onehot[lr] * d_e[:, None]             # (nnz, k)
            partial = jax.ops.segment_sum(contrib2, cc,
                                          num_segments=centers.shape[1])
            sums = lax.psum(partial, _mesh.ROWS).T           # (k, n)
            inertia = lax.psum(
                jnp.sum(jnp.min(dist, axis=1)
                        * valid.astype(centers.dtype)), _mesh.ROWS)
            new_centers = jnp.where(counts[:, None] > 0,
                                    sums / jnp.maximum(counts, 1.0)[:, None],
                                    centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, shift, it + 1, inertia, hist.at[it].set(inertia)

        def cond(carry):
            _, shift, it, _, _ = carry
            return (it < max_iter) & (shift >= tol)

        init = (c0, jnp.asarray(jnp.inf, c0.dtype), jnp.int32(0),
                jnp.asarray(0.0, c0.dtype), jnp.zeros((max_iter,), c0.dtype))
        return lax.while_loop(cond, step, init)

    from jax.sharding import PartitionSpec as P
    # replication checking stays ON: every loop-carry element descends from
    # psum outputs, so the varying-axes analysis proves the P() out_specs
    centers, shift, n_iter, inertia, hist = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS),
                  P(None, None)),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=True,
    )(data, lrows, cols, rowsq, centers0)
    # fused health vector over the replicated outputs — still inside this
    # jitted program, zero extra dispatches
    hvec = _health.health_vec(carries=(centers,), hist=hist, n_done=n_iter)
    return centers, n_iter, inertia, shift, hist, hvec


@partial(_pjit, static_argnames=("shape",), name="kmeans_score")
@precise
def _kmeans_score(xp, shape, centers):
    m, n = shape
    xv = xp[:, :n]
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m).astype(xv.dtype)
    d = _distances_sq(xv, centers)
    return -jnp.sum(jnp.min(d, axis=1) * w)
