from dislib_tpu.cluster.kmeans import KMeans

__all__ = ["KMeans"]
