from dislib_tpu.cluster.kmeans import KMeans
from dislib_tpu.cluster.gm import GaussianMixture

__all__ = ["KMeans", "GaussianMixture"]
