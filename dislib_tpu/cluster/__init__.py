from dislib_tpu.cluster.kmeans import KMeans
from dislib_tpu.cluster.minibatch import MiniBatchKMeans
from dislib_tpu.cluster.gm import GaussianMixture
from dislib_tpu.cluster.dbscan import DBSCAN
from dislib_tpu.cluster.daura import Daura

__all__ = ["KMeans", "MiniBatchKMeans", "GaussianMixture", "DBSCAN",
           "Daura"]
