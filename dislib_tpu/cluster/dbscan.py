"""DBSCAN (reference: `dislib/cluster/dbscan` — `base.py`/`classes.py`:
spatial `Region` grid partition, per-region local sklearn DBSCAN on
region+ε-halo samples, cross-region label-equivalence merge via union-find;
SURVEY.md §3.3 "hardest estimator to make SPMD").

TPU-native redesign — NOT a region-graph translation:

The reference partitions space into `n_regions` grid cells because no CPU
worker can hold all pairwise distances, then pays a union-find merge over
region transition lists.  On a TPU mesh the ε-neighborhood relation of the
whole (row-sharded) dataset is one distance GEMM (MXU-bound), and the
cross-region union-find becomes *connected components by min-label
propagation with pointer jumping* — a `lax.while_loop` of masked min-reduces
and gathers that converges in O(log n) rounds and runs entirely on device:

- core points: ε-neighbor counts from the distance matrix (one reduce);
- cluster labels over the core-core graph: ``label ← min(label, min over
  core neighbors)`` followed by ``label ← label[label]`` (pointer jump);
- border points take the min label among adjacent core points; the rest is
  noise (−1).

The grid-partition knobs of the reference (`n_regions`, `dimensions`,
`max_samples`) are accepted for API parity and ignored: spatial partitioning
was a memory/scheduling device of the task runtime, not algorithm semantics.

Scale: fit sets whose padded row count exceeds `_DENSE_MAX` switch from the
resident m×m adjacency to the streamed tile passes of `ops/tiled.py` —
every reduce (core counts, per-round min-label propagation, border labels)
is the same math over (tile × tile) distance pieces, so peak memory is
O(tile²), at the cost of recomputing distance GEMMs each propagation round
(the reference's region grid made the same memory-for-recompute trade at
the task level).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad, \
    ensure_canonical as _ensure_canonical
from dislib_tpu.ops import distances_sq
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops.base import precise
from dislib_tpu.ops import tiled as _tiled
from dislib_tpu.ops.ring import ring_auto, ring_neigh_count_min
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health

# padded row counts above this stream the adjacency in tiles instead of
# materialising the m×m matrix (module-level so tests can force the path)
_DENSE_MAX = 16384

# ring-distribute the streamed passes over the mesh 'rows' axis when the
# mesh has >1 row shard and the fit crosses this padded-row threshold;
# None = auto, True/False force (module-level so tests can force the path)
_RING = None


class DBSCAN(BaseEstimator):
    """Density-based clustering.

    Parameters (reference parity)
    ----------
    eps : float, default 0.5 — ε-neighborhood radius.
    min_samples : int, default 5 — neighbors (incl. self) to be a core point.
    n_regions, dimensions, max_samples — accepted and ignored (reference
        task-partitioning knobs; see module docstring).

    Attributes
    ----------
    labels_ : ndarray (n_samples,) int — cluster ids 0..k−1, noise = −1.
    n_clusters_ : int
    core_sample_indices_ : ndarray int — indices of core points.
    """

    def __init__(self, eps=0.5, min_samples=5, n_regions=1, dimensions=None,
                 max_samples=None):
        self.eps = eps
        self.min_samples = min_samples
        self.n_regions = n_regions
        self.dimensions = dimensions
        self.max_samples = max_samples

    def fit(self, x: Array, y=None, checkpoint=None, health=None):
        """Fit.  With ``checkpoint=FitCheckpoint(path, every=k)`` the label
        vector snapshots every k propagation rounds (the per-pass boundary
        — SURVEY §6 checkpoint/resume) on whichever streamed tier the
        plain fit would pick — ring on a multi-row mesh, tiled otherwise,
        so scale-out and fault tolerance compose.  A re-run resumes the
        propagation from the snapshot and lands on the uninterrupted
        run's clustering (min-label propagation is monotone in the label
        vector, so resuming from any intermediate state is exact).

        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`.
        Labels are integral (no numeric trajectory to diverge), so the
        fused guard watches the INPUT: a non-finite coordinate makes
        every ε-comparison silently false (all-noise clustering) — the
        guard raises a typed ``NumericalDivergence`` instead (quarantine
        the rows at ingest).  The chunk watchdog covers hung passes."""
        mesh = _mesh.get_mesh()
        # ring-tier shard_map splits rows over the mesh — an input built
        # under another mesh re-lays out on device (never a host hop)
        x = _ensure_canonical(x)
        if checkpoint is not None:
            raw, core = self._fit_checkpointed(x, checkpoint, mesh, health)
        else:
            def step(st, chunk):
                if ring_auto(_RING, mesh, x._data.shape[0] > _DENSE_MAX):
                    # rotate/compute schedule: resolved at this host
                    # boundary (DSLIB_OVERLAP flips retrace via the static)
                    sched = _ov.resolve()
                    _prof.count_schedule("ring_neigh", sched)
                    raw, core, hvec = _dbscan_fit_ring(
                        x._data, x.shape, float(self.eps),
                        int(self.min_samples), mesh, overlap=sched)
                elif x._data.shape[0] <= _DENSE_MAX:
                    raw, core, hvec = _dbscan_fit(x._data, x.shape,
                                                  float(self.eps),
                                                  int(self.min_samples))
                else:
                    # single-device tiled tier: no collective to overlap,
                    # but the pallas route still picks the inner kernel
                    sched = _ov.resolve()
                    _prof.count_schedule("tiled_neigh", sched)
                    raw, core, hvec = _dbscan_fit_tiled(
                        x._data, x.shape, float(self.eps),
                        int(self.min_samples), _tiled.TILE,
                        use_pallas=(sched == "pallas"))
                return _fitloop.ChunkOutcome(
                    _fitloop.LoopState((), 0, True, extra=(raw, core)),
                    hvec=hvec)      # input faults: typed raise via the loop

            loop = _fitloop.ChunkedFitLoop("dbscan", health=health)
            st = loop.run(init=lambda rem: _fitloop.LoopState(()), step=step)
            self.fit_info_ = loop.info
            raw, core = st.extra
        raw = np.asarray(jax.device_get(raw))[: x.shape[0]]
        core = np.asarray(jax.device_get(core))[: x.shape[0]]
        # renumber root labels compactly in order of first appearance
        # (vectorised: roots sorted by their first occurrence index)
        clustered = raw >= 0
        roots, first, inverse = np.unique(raw[clustered], return_index=True,
                                          return_inverse=True)
        rank = np.empty(len(roots), dtype=np.int64)
        rank[np.argsort(first, kind="stable")] = np.arange(len(roots))
        labels = np.full(x.shape[0], -1, dtype=np.int64)
        labels[clustered] = rank[inverse]
        self.labels_ = labels
        self.n_clusters_ = len(roots)
        self.core_sample_indices_ = np.nonzero(core)[0]
        return self

    def fit_predict(self, x: Array, y=None) -> Array:
        self.fit(x)
        lab = jnp.asarray(self.labels_.astype(np.int32)[:, None])
        return Array._from_logical_padded(_repad(lab, (x.shape[0], 1)),
                                          (x.shape[0], 1))

    def _fit_checkpointed(self, x: Array, checkpoint, mesh, health=None):
        """Chunked fit: `every` propagation rounds per dispatch, the
        (label, core) state snapshotted between chunks.  The ring tier is
        picked by the same policy as the plain fit (scale-out and fault
        tolerance compose); otherwise the tiled tier runs at any size
        (the chunk boundary is what checkpointing needs).  The snapshot
        format is tier-independent except for the pad width, which the
        fingerprint pins (a resume on a different mesh/tier refuses
        rather than mixing label paddings)."""
        from dislib_tpu.utils.checkpoint import data_digest, validate_snapshot
        eps, ms = float(self.eps), int(self.min_samples)
        m = x.shape[0]
        box = {"x": x}

        def _stage(cur_mesh):
            # tier selection + the tier closures, re-run by the elastic
            # rebind: a mesh change re-picks the ring/tiled tier for the
            # NEW topology and re-binds the closures to the re-laid-out
            # backing (the snapshot format is tier-independent — labels
            # are core row ids with a sentinel the restore re-bases)
            xd = box["x"]._data
            if ring_auto(_RING, cur_mesh, xd.shape[0] > _DENSE_MAX):
                mp = xd.shape[0]
                sched = _ov.resolve()
                _prof.count_schedule("ring_neigh", sched)

                def setup():
                    return _dbscan_setup_ring(xd, x.shape, eps, ms,
                                              cur_mesh, overlap=sched)

                def propagate(lab, core):
                    return _dbscan_propagate_ring(
                        xd, eps, lab, core, cur_mesh,
                        max_rounds=checkpoint.every, overlap=sched)

                def finalize(lab, core):
                    return _dbscan_finalize_ring(xd, x.shape, eps, lab,
                                                 core, cur_mesh,
                                                 overlap=sched)
            else:
                mp = -(-xd.shape[0] // _tiled.TILE) * _tiled.TILE
                # single-device tiled tier: the pallas route picks the
                # inner kernel (no collective to overlap)
                sched = _ov.resolve()
                _prof.count_schedule("tiled_neigh", sched)
                pall = sched == "pallas"

                def setup():
                    return _dbscan_setup_tiled(xd, x.shape, eps, ms,
                                               _tiled.TILE, use_pallas=pall)

                def propagate(lab, core):
                    return _dbscan_propagate_tiled(
                        xd, x.shape, eps, lab, core, _tiled.TILE,
                        max_rounds=checkpoint.every, use_pallas=pall)

                def finalize(lab, core):
                    return _dbscan_finalize_tiled(xd, x.shape, eps, lab,
                                                  core, _tiled.TILE,
                                                  use_pallas=pall)
            box.update(mp=mp, setup=setup, propagate=propagate,
                       finalize=finalize)

        _stage(mesh)
        _data_hook = _fitloop.data_rebind(box)

        def rebind(new_mesh):
            _data_hook(new_mesh)        # force chains / re-canonicalize x
            if new_mesh is not None:
                _stage(new_mesh)

        # the pad width is NOT fingerprinted (round 16): labels re-base
        # their sentinel on restore, so a snapshot resumes on any
        # mesh/tier instead of refusing on a pad-width mismatch
        fp = np.asarray([x.shape[0], x.shape[1], eps, ms], np.float64)
        digest = data_digest(x._data)
        loop = _fitloop.ChunkedFitLoop("dbscan", checkpoint=checkpoint,
                                       health=health, elastic=rebind)

        def init(rem):
            core, label = box["setup"]()
            return _fitloop.LoopState((label,), extra=core)

        def restore(snap, rem):
            validate_snapshot(snap, fp, digest)
            mp = box["mp"]
            lab = np.asarray(snap["label"])
            core = np.asarray(snap["core"])
            # sentinel re-base: labels are core ROW ids (always < m) with
            # "no label" = the WRITER's pad width; crop to the logical
            # rows, re-base the sentinel to THIS pad width, and re-pad —
            # pad rows are never core, so sentinel/False fills are exact
            lab = np.where(lab[:m] < m, lab[:m], mp).astype(lab.dtype)
            lab = np.pad(lab, (0, mp - m), constant_values=mp)
            core = np.pad(core[:m], (0, mp - m))
            return _fitloop.LoopState((jnp.asarray(lab),),
                                      extra=jnp.asarray(core))

        def step(st, chunk):
            (label,) = st.carries
            label, changed, hvec = box["propagate"](label, st.extra)
            # state deferred: the watchdogged hvec read (the chunk force
            # point) precedes the `changed` convergence fetch
            return _fitloop.ChunkOutcome(
                lambda: _fitloop.LoopState((label,), st.it + 1,
                                           not bool(_fetch(changed)),
                                           extra=st.extra),
                hvec=hvec)

        def snapshot(st):
            # blocking fetches, async file write (overlaps next propagate)
            return {"label": _fetch(st.carries[0]), "core": _fetch(st.extra),
                    "fp": fp, "digest": digest}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        self.fit_info_ = loop.info
        return box["finalize"](st.carries[0], st.extra), st.extra


@partial(jax.jit, static_argnames=("shape", "min_samples"))
@precise
def _dbscan_fit(xp, shape, eps, min_samples):
    m, n = shape
    xv = xp[:, :n]
    mp = xv.shape[0]                       # padded row count
    sentinel = jnp.int32(mp)               # "no label"

    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    d2 = distances_sq(xv, xv)
    adj = (d2 <= eps * eps) & valid[:, None] & valid[None, :]
    # self-distance is mathematically 0: make the diagonal structurally True
    # so fp rounding in the distance GEMM can't drop self-neighborship
    adj = adj | (jnp.eye(mp, dtype=jnp.bool_) & valid[:, None])

    core = (jnp.sum(adj, axis=1) >= min_samples) & valid
    core_adj = adj & core[:, None] & core[None, :]

    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)
    label0 = jnp.where(core, ids, sentinel)

    def body(carry):
        label, _ = carry
        # min label among core neighbors (row i of core_adj is all-False for
        # non-core i, so non-core labels stay at the sentinel)
        neigh = jnp.where(core_adj, label[None, :], sentinel)
        new = jnp.minimum(label, jnp.min(neigh, axis=1))
        # pointer jump: follow the label one hop (path halving)
        jumped = jnp.where(new < sentinel, new[jnp.minimum(new, mp - 1)], sentinel)
        new = jnp.minimum(new, jumped)
        return new, jnp.any(new != label)

    def cond(carry):
        return carry[1]

    label, _ = lax.while_loop(cond, body, (label0, jnp.bool_(True)))

    # border points: min label among adjacent core points
    border_neigh = jnp.where(adj & core[None, :], label[None, :], sentinel)
    border_label = jnp.min(border_neigh, axis=1)
    final = jnp.where(core, label, jnp.where(valid, border_label, sentinel))
    final = jnp.where(final < sentinel, final, -1)
    # fused input guard — a non-finite coordinate silently fails every
    # ε-comparison (all-noise output), so it must trip, not pass through
    hvec = _health.health_vec(inputs=(jnp.where(valid[:, None], xv, 0.0),))
    return final, core, hvec


@partial(jax.jit, static_argnames=("shape", "min_samples", "tile",
                                   "use_pallas"))
@precise
def _dbscan_setup_tiled(xp, shape, eps, min_samples, tile, use_pallas=False):
    """Tiled tier, phase 1: core mask + initial labels (one ε-pass)."""
    m, n = shape
    xv, _ = _tiled.pad_to_tiles(xp[:, :n], tile)
    mp = xv.shape[0]
    sentinel = jnp.int32(mp)
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)
    counts, _ = _tiled.neigh_count_min(xv, eps * eps, ids, valid, sentinel,
                                       tile, use_pallas=use_pallas)
    core = (counts >= min_samples) & valid
    return core, jnp.where(core, ids, sentinel)


@partial(jax.jit, static_argnames=("shape", "tile", "max_rounds",
                                   "use_pallas"))
@precise
def _dbscan_propagate_tiled(xp, shape, eps, label, core, tile, max_rounds,
                            use_pallas=False):
    """Tiled tier, phase 2: ≤ max_rounds min-label propagation rounds with
    pointer jumping.  Returns (label, changed) — ``changed`` True means the
    bound was hit mid-propagation and the caller must run another chunk
    (the mid-fit checkpoint boundary; SURVEY §6)."""
    m, n = shape
    xv, _ = _tiled.pad_to_tiles(xp[:, :n], tile)
    mp = xv.shape[0]
    sentinel = jnp.int32(mp)

    def body(carry):
        lab, _, it = carry
        _, neigh_min = _tiled.neigh_count_min(xv, eps * eps, lab, core,
                                              sentinel, tile,
                                              use_pallas=use_pallas)
        new = jnp.where(core, jnp.minimum(lab, neigh_min), sentinel)
        jumped = jnp.where(new < sentinel, new[jnp.minimum(new, mp - 1)],
                           sentinel)
        new = jnp.minimum(new, jumped)
        return new, jnp.any(new != lab), it + 1

    def cond(carry):
        return carry[1] & (carry[2] < max_rounds)

    label, changed, _ = lax.while_loop(
        cond, body, (label, jnp.bool_(True), jnp.int32(0)))
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    hvec = _health.health_vec(inputs=(jnp.where(valid[:, None], xv, 0.0),))
    return label, changed, hvec


@partial(jax.jit, static_argnames=("shape", "tile", "use_pallas"))
@precise
def _dbscan_finalize_tiled(xp, shape, eps, label, core, tile,
                           use_pallas=False):
    """Tiled tier, phase 3: border labels + compact -1 noise encoding."""
    m, n = shape
    xv, _ = _tiled.pad_to_tiles(xp[:, :n], tile)
    mp = xv.shape[0]
    sentinel = jnp.int32(mp)
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    _, border_label = _tiled.neigh_count_min(xv, eps * eps, label, core,
                                             sentinel, tile,
                                             use_pallas=use_pallas)
    final = jnp.where(core, label, jnp.where(valid, border_label, sentinel))
    return jnp.where(final < sentinel, final, -1)


def _dbscan_fit_tiled(xp, shape, eps, min_samples, tile, use_pallas=False):
    """Same algorithm as `_dbscan_fit`, adjacency streamed in tiles — the
    distance GEMM is recomputed per propagation round (O(log n) rounds via
    pointer jumping) instead of held resident.  Expressed as
    setup → propagate(unbounded) → finalize, the same three programs the
    checkpointed fit runs in bounded chunks."""
    core, label0 = _dbscan_setup_tiled(xp, shape, eps, min_samples, tile,
                                       use_pallas=use_pallas)
    label, _, hvec = _dbscan_propagate_tiled(xp, shape, eps, label0, core,
                                             tile, max_rounds=1 << 30,
                                             use_pallas=use_pallas)
    return (_dbscan_finalize_tiled(xp, shape, eps, label, core, tile,
                                   use_pallas=use_pallas), core, hvec)


@partial(jax.jit, static_argnames=("shape", "min_samples", "mesh",
                                   "overlap"))
@precise
def _dbscan_setup_ring(xp, shape, eps, min_samples, mesh, overlap="db"):
    """Ring tier, phase 1: core mask + initial labels (one ring ε-pass)."""
    m, _ = shape
    mp = xp.shape[0]
    sentinel = jnp.int32(mp)
    eps2 = jnp.asarray(eps * eps, xp.dtype)
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    ids = lax.broadcasted_iota(jnp.int32, (mp,), 0)
    counts, _ = ring_neigh_count_min(xp, eps2, ids, valid, sentinel, mesh,
                                     overlap=overlap)
    core = (counts >= min_samples) & valid
    return core, jnp.where(core, ids, sentinel)


@partial(jax.jit, static_argnames=("mesh", "max_rounds", "overlap"))
@precise
def _dbscan_propagate_ring(xp, eps, label, core, mesh, max_rounds,
                           overlap="db"):
    """Ring tier, phase 2: ≤ max_rounds propagation rounds (checkpoint
    chunk boundary, same contract as the tiled variant).  Needs no
    logical shape: validity is already encoded in `core`, and the ring
    pass relies on the zero-pad invariant for feature columns."""
    mp = xp.shape[0]
    sentinel = jnp.int32(mp)
    eps2 = jnp.asarray(eps * eps, xp.dtype)

    def body(carry):
        lab, _, it = carry
        _, neigh_min = ring_neigh_count_min(xp, eps2, lab, core, sentinel,
                                            mesh, overlap=overlap)
        new = jnp.where(core, jnp.minimum(lab, neigh_min), sentinel)
        jumped = jnp.where(new < sentinel, new[jnp.minimum(new, mp - 1)],
                           sentinel)
        new = jnp.minimum(new, jumped)
        return new, jnp.any(new != lab), it + 1

    label, changed, _ = lax.while_loop(
        lambda c: c[1] & (c[2] < max_rounds), body,
        (label, jnp.bool_(True), jnp.int32(0)))
    # pad rows are zero under the pad-and-mask invariant, so the raw
    # backing is safe to scan for non-finite input coordinates
    hvec = _health.health_vec(inputs=(xp,))
    return label, changed, hvec


@partial(jax.jit, static_argnames=("shape", "mesh", "overlap"))
@precise
def _dbscan_finalize_ring(xp, shape, eps, label, core, mesh, overlap="db"):
    """Ring tier, phase 3: border labels + compact -1 noise encoding."""
    m, _ = shape
    mp = xp.shape[0]
    sentinel = jnp.int32(mp)
    eps2 = jnp.asarray(eps * eps, xp.dtype)
    valid = lax.broadcasted_iota(jnp.int32, (mp,), 0) < m
    _, border_label = ring_neigh_count_min(xp, eps2, label, core, sentinel,
                                           mesh, overlap=overlap)
    final = jnp.where(core, label, jnp.where(valid, border_label, sentinel))
    return jnp.where(final < sentinel, final, -1)


def _dbscan_fit_ring(xp, shape, eps, min_samples, mesh, overlap="db"):
    """Same algorithm as `_dbscan_fit_tiled`, ε-passes ring-distributed over
    the mesh 'rows' axis (`ops/ring.ring_neigh_count_min`): each device
    keeps only its row shard resident, label vectors stay row-sharded, and
    the pointer-jump gather is a sharded global gather handled by SPMD.
    Expressed as setup → propagate(unbounded) → finalize, the same three
    programs the checkpointed ring fit runs in bounded chunks."""
    core, label0 = _dbscan_setup_ring(xp, shape, eps, min_samples, mesh,
                                      overlap=overlap)
    label, _, hvec = _dbscan_propagate_ring(xp, eps, label0, core, mesh,
                                            max_rounds=1 << 30,
                                            overlap=overlap)
    return (_dbscan_finalize_ring(xp, shape, eps, label, core, mesh,
                                  overlap=overlap), core, hvec)
