"""Gaussian mixture via EM (reference: `dislib/cluster/gm` — per-block E-step
responsibility tasks + M-step partial-sum tasks, Cholesky precisions per
component, per-iteration host sync on log-likelihood; SURVEY.md §3.3,
BASELINE config 5).

TPU-native redesign, same shape as KMeans (§4.2 mapping): the whole EM loop
is one jitted `lax.while_loop` on device.  The E-step's per-block
log-prob/responsibility tasks become batched GEMMs over the row-sharded data
(the Mahalanobis term is one (m, d) @ (d, d) matmul per component, vmapped);
the M-step's arity-tree partial sums (weights / means / covariances) are the
row-axis reductions XLA lowers to `psum` over ICI.  Convergence on the
log-likelihood delta happens on device; the host syncs once per fit.

All four covariance types of the reference are supported: full, tied, diag,
spherical.  Padded (zero) rows carry weight 0 everywhere.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, ensure_canonical, fused_kernel
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise
from dislib_tpu.utils.profiling import profiled_jit as _pjit
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health
from dislib_tpu.utils.dlog import verbose_logger

_LOG2PI = float(np.log(2.0 * np.pi))


class GaussianMixture(BaseEstimator):
    """Gaussian mixture model (reference parity: dislib.cluster.GaussianMixture).

    Parameters
    ----------
    n_components : int, default 1
    covariance_type : 'full' | 'tied' | 'diag' | 'spherical'
    tol : float — convergence threshold on the lower-bound delta.
    reg_covar : float — ridge added to covariance diagonals.
    max_iter : int
    init_params : 'kmeans' | 'random'
    weights_init, means_init, precisions_init : optional explicit inits
        (reference parity).
    arity : int — accepted, ignored (reduction topology is XLA's).
    random_state : int or None

    Attributes
    ----------
    weights_, means_, covariances_ : ndarrays
    converged_ : bool ;  n_iter_ : int ;  lower_bound_ : float
    history_ : ndarray (n_iter_,) — per-iteration lower bound (SURVEY §6).
    """

    def __init__(self, n_components=1, covariance_type="full", tol=1e-3,
                 reg_covar=1e-6, max_iter=100, init_params="kmeans",
                 weights_init=None, means_init=None, precisions_init=None,
                 arity=50, random_state=None, verbose=False):
        self.n_components = n_components
        self.covariance_type = covariance_type
        self.tol = tol
        self.reg_covar = reg_covar
        self.max_iter = max_iter
        self.init_params = init_params
        self.weights_init = weights_init
        self.means_init = means_init
        self.precisions_init = precisions_init
        self.arity = arity
        self.random_state = random_state
        self.verbose = verbose

    # ------------------------------------------------------------------

    def _init_resp(self, x: Array):
        """Initial responsibilities (m_pad, k) — hard KMeans labels or random."""
        m, n = x.shape
        k = self.n_components
        if self.init_params == "kmeans":
            # run the KMeans device kernels directly so the init stays on
            # device end-to-end — no host read between here and the EM loop
            # (keeps `_fit_async` dispatch-only for GridSearchCV, SURVEY §4.5)
            from dislib_tpu.cluster.kmeans import (KMeans, _kmeans_fit,
                                                   _kmeans_predict)
            km = KMeans(n_clusters=k, max_iter=10, tol=1e-4,
                        random_state=self.random_state)
            centers = _kmeans_fit(x._data, x.shape, km._init_centers(x),
                                  10, 1e-4, fast=km._fast())[0]
            labels = _kmeans_predict(x._data, x.shape, centers)[:, 0]
            resp = jax.nn.one_hot(labels, k, dtype=jnp.float32)
        elif self.init_params == "random":
            seed = 0 if self.random_state is None else int(self.random_state)
            resp = jax.random.uniform(jax.random.PRNGKey(seed),
                                      (x._data.shape[0], k), dtype=jnp.float32)
            resp = resp / jnp.sum(resp, axis=1, keepdims=True)
        else:
            raise ValueError(f"unsupported init_params {self.init_params!r}")
        return resp

    def fit(self, x: Array, y=None, checkpoint=None, health=None):
        """Fit by EM.  With ``checkpoint=FitCheckpoint(path, every=k)`` the
        device loop runs in k-iteration chunks, snapshotting (weights, means,
        covariances, lower_bound, n_iter) after each; a re-run resumes from
        the snapshot (SURVEY §6 checkpoint/resume).

        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`;
        each chunk's kernel emits a fused health vector over the EM
        parameters and the lower-bound history (monotone nondecreasing).
        A tripped guard rolls back to the last-good snapshot; the
        ``halve`` action additionally doubles ``reg_covar`` per restart
        (the EM damping knob — a collapsing component's singular
        covariance is the classic EM failure)."""
        if self.covariance_type not in ("full", "tied", "diag", "spherical"):
            raise ValueError(f"bad covariance_type {self.covariance_type!r}")
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        m, n = x.shape
        box = {"x": x, "reg_covar": float(self.reg_covar), "resp0": None,
               "lb": None}
        log = verbose_logger("gm", self.verbose)
        loop = _fitloop.ChunkedFitLoop(
            "gm", checkpoint=checkpoint, health=health,
            max_iter=self.max_iter,
            increasing=True,            # EM lower bound must not fall
            carry_names=("weights", "means", "covariances"),
            carry_shapes=((self.n_components,), (self.n_components, n)),
            snapshot_expect={"weights": (self.n_components,),
                             "means": (self.n_components, n)},
            elastic=_fitloop.data_rebind(box))

        def init(rem):
            # EM damping: the 'halve' escalation tier raises the
            # covariance ridge per tier attempt, the standard fix for a
            # component collapsing onto a point (singular covariance→NaN)
            box["reg_covar"] = float(self.reg_covar) * rem.damping
            box["resp0"] = self._init_resp(box["x"])
            box["lb"] = None
            return _fitloop.LoopState(self._explicit_inits(n))

        def restore(snap, rem):
            # resume: all three parameters come from the snapshot, so skip
            # the (KMeans-based) responsibility init entirely
            box["reg_covar"] = float(self.reg_covar) * rem.damping
            box["resp0"] = jnp.zeros((box["x"]._data.shape[0],
                                      self.n_components), jnp.float32)
            # weights/means compatibility is declared via snapshot_expect
            # and judged by the rollback funnel
            ov = tuple(jnp.asarray(rem.perturb(snap[k])) for k in
                       ("weights", "means", "covariances"))
            box["lb"] = float(snap["lower_bound"])
            return _fitloop.LoopState(ov, it=int(snap["n_iter"]),
                                      done=bool(snap.get("converged", False)))

        def step(st, chunk):
            xd = box["x"]
            weights, means, covs, lb_dev, n_done, conv, hist, hvec = _gm_fit(
                xd._data, xd.shape, box["resp0"], self.covariance_type,
                box["reg_covar"], float(self.tol), chunk, st.carries,
                prev_lb0=box["lb"])

            def commit():
                # deferred scalar syncs: the watchdogged hvec read stays
                # the chunk's first force point
                box["lb"] = float(lb_dev)
                it = st.it + int(n_done)
                log.info("iter %d: lower_bound=%.6g", it, box["lb"])
                return _fitloop.LoopState((weights, means, covs), it,
                                          bool(conv))

            return _fitloop.ChunkOutcome(
                commit, hvec=hvec,
                history=lambda: _fetch(hist)[: int(n_done)])

        def snapshot(st):
            # the EM parameters are DONATED to the next chunk's kernel
            # (HBM reused in place), so their device->host copies are
            # blocking; the checksum+file write still overlaps the next
            # chunk on the snapshot worker
            weights, means, covs = st.carries
            return {"weights": _fetch(weights), "means": _fetch(means),
                    "covariances": _fetch(covs), "lower_bound": box["lb"],
                    "n_iter": st.it, "converged": st.done}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        weights, means, covs = st.carries
        self.weights_ = np.asarray(jax.device_get(weights))
        self.means_ = np.asarray(jax.device_get(means))
        self.covariances_ = np.asarray(jax.device_get(covs))
        self.lower_bound_ = box["lb"] if box["lb"] is not None else -np.inf
        self.n_iter_ = st.it
        self.converged_ = st.done
        self.history_ = np.asarray(loop.history, dtype=np.float64)
        self.fit_info_ = loop.info
        return self

    def score(self, x: Array, y=None) -> float:
        """Mean per-sample log-likelihood under the fitted mixture (sklearn
        convention) — also what GridSearchCV maximises by default."""
        self._check_fitted()
        return float(_gm_loglik(x._data, x.shape, jnp.asarray(self.weights_),
                                jnp.asarray(self.means_),
                                jnp.asarray(self.covariances_),
                                self.covariance_type))

    # async trial protocol (SURVEY §4.5): the whole EM fit — including the
    # KMeans init — is device dispatch only; GridSearchCV reads nothing back
    # until every trial is in flight
    def _fit_async(self, x, y=None):
        if self.covariance_type not in ("full", "tied", "diag", "spherical"):
            raise ValueError(f"bad covariance_type {self.covariance_type!r}")
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        resp0 = self._init_resp(x)
        overrides = self._explicit_inits(x.shape[1])
        return _gm_fit(x._data, x.shape, resp0, self.covariance_type,
                       float(self.reg_covar), float(self.tol), self.max_iter,
                       overrides)

    def _fit_finalize(self, state):
        if state is None:
            return
        weights, means, covs, lb, n_iter, conv, hist, _ = state
        self.weights_ = np.asarray(jax.device_get(weights))
        self.means_ = np.asarray(jax.device_get(means))
        self.covariances_ = np.asarray(jax.device_get(covs))
        self.lower_bound_ = float(lb)
        self.n_iter_ = int(n_iter)
        self.converged_ = bool(conv)
        self.history_ = np.asarray(
            jax.device_get(hist), dtype=np.float64)[: self.n_iter_]

    def _score_async(self, state, x, y=None):
        if state is None:
            return super()._score_async(state, x, y)
        weights, means, covs = state[0], state[1], state[2]
        return _gm_loglik(x._data, x.shape, weights, means, covs,
                          self.covariance_type)

    def _explicit_inits(self, d):
        """(weights, means, covs) overrides from the *_init params (reference
        parity: weights_init / means_init / precisions_init)."""
        w = None if self.weights_init is None else \
            jnp.asarray(np.asarray(self.weights_init, np.float32))
        mu = None if self.means_init is None else \
            jnp.asarray(np.asarray(self.means_init, np.float32))
        covs = None
        if self.precisions_init is not None:
            p = np.asarray(self.precisions_init, np.float64)
            if self.covariance_type == "full":
                covs = jnp.asarray(np.linalg.inv(p).astype(np.float32))
            elif self.covariance_type == "tied":
                covs = jnp.asarray(np.linalg.inv(p).astype(np.float32))
            else:  # diag / spherical: precisions are 1/variances
                covs = jnp.asarray((1.0 / p).astype(np.float32))
        return (w, mu, covs)

    def fit_predict(self, x: Array, y=None) -> Array:
        return self.fit(x).predict(x)

    def predict(self, x: Array) -> Array:
        """Component index per row — a fusion-graph node, so a scaler →
        predict pipeline is ONE cached dispatch (the serving hot path)."""
        self._check_fitted()
        # serve on the CURRENT mesh: an input built before an elastic
        # resize re-lands on device (never the host) — round 16
        x = ensure_canonical(x)
        weights, means, covs = self._predict_leaves(
            self.weights_, self.means_, self.covariances_)
        return fused_kernel(
            _gm_predict_kernel, (x.shape, self.covariance_type),
            (x, weights, means, covs), (x.shape[0], 1), jnp.int32,
            out_pshape=(x._pshape[0], 1))

    def _check_fitted(self):
        if not hasattr(self, "means_"):
            raise RuntimeError("GaussianMixture is not fitted")


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _chol_precisions(covs, cov_type, d):
    """Cholesky factors of the precision matrices (sklearn-style)."""
    if cov_type == "full":
        chol = jnp.linalg.cholesky(covs)                      # (k, d, d)
        prec = jax.vmap(lambda c: jax.scipy.linalg.solve_triangular(
            c, jnp.eye(d, dtype=c.dtype), lower=True).T)(chol)
        return prec                                           # (k, d, d) upper
    if cov_type == "tied":
        chol = jnp.linalg.cholesky(covs)                      # (d, d)
        return jax.scipy.linalg.solve_triangular(
            chol, jnp.eye(d, dtype=chol.dtype), lower=True).T
    # diag (k, d) / spherical (k,)
    return 1.0 / jnp.sqrt(covs)


def _log_prob(xv, means, prec, cov_type, d):
    """Weighted log N(x | mu_k, Sigma_k): (m, k)."""
    if cov_type == "full":
        # maha_ik = ‖x_i P_k − μ_k P_k‖², expanded so no (k, m, d) DIFF
        # intermediate materialises in HBM: the batched GEMM z = x @ P_k is
        # the only (k, m, d) tensor, and the square-sum + dot against
        # t_k = μ_k P_k fuse into its single read-back.  Same cancellation
        # profile as ops.distances_sq (clamped at zero).
        def per_comp(mu, pc):
            z = xv @ pc                                       # (m, d) GEMM
            t = mu @ pc                                       # (d,)
            maha = jnp.maximum(
                jnp.sum(z * z, axis=1) - 2.0 * (z @ t) + t @ t, 0.0)
            return maha, jnp.sum(jnp.log(jnp.diag(pc)))
        maha, logdet = jax.vmap(per_comp)(means, prec)
        return -0.5 * (d * _LOG2PI + maha.T) + logdet[None, :]
    if cov_type == "tied":
        y = xv @ prec                                         # (m, d)
        mu_p = means @ prec                                   # (k, d)
        maha = (jnp.sum(y * y, axis=1)[:, None] - 2.0 * y @ mu_p.T
                + jnp.sum(mu_p * mu_p, axis=1)[None, :])
        logdet = jnp.sum(jnp.log(jnp.diag(prec)))
        return -0.5 * (d * _LOG2PI + maha) + logdet
    if cov_type == "diag":
        p2 = prec * prec                                      # (k, d)
        maha = ((xv * xv) @ p2.T - 2.0 * xv @ (means * p2).T
                + jnp.sum(means * means * p2, axis=1)[None, :])
        logdet = jnp.sum(jnp.log(prec), axis=1)
        return -0.5 * (d * _LOG2PI + maha) + logdet[None, :]
    # spherical
    p2 = prec * prec                                          # (k,)
    sq = (jnp.sum(xv * xv, axis=1)[:, None] - 2.0 * xv @ means.T
          + jnp.sum(means * means, axis=1)[None, :])
    maha = sq * p2[None, :]
    logdet = d * jnp.log(prec)
    return -0.5 * (d * _LOG2PI + maha) + logdet[None, :]


def _estimate_covs(xv, resp, nk, means, cov_type, reg_covar, w):
    """M-step covariance update; resp already includes the row mask."""
    d = xv.shape[1]
    if cov_type == "full":
        # √r-weighted single intermediate: wd = √r_k (x − μ_k) makes the
        # covariance wdᵀwd — symmetric PSD by construction, and only ONE
        # (k, m, d) tensor reaches HBM (the diff and the weighting fuse
        # into its materialisation) instead of the two that diff-then-
        # weight would write.  r_k ≥ 0 always (responsibilities × mask).
        def per_comp(r_k, mu, n_k):
            wd = (xv - mu[None, :]) * jnp.sqrt(r_k)[:, None]
            return wd.T @ wd / n_k + reg_covar * jnp.eye(d, dtype=xv.dtype)
        return jax.vmap(per_comp)(resp.T, means, nk)
    if cov_type == "tied":
        # Σ_total = XᵀWX - Σ_k n_k μ_k μ_kᵀ, averaged
        xw = xv * w[:, None]
        avg_x2 = xw.T @ xv
        avg_mu2 = (means * nk[:, None]).T @ means
        cov = (avg_x2 - avg_mu2) / jnp.sum(nk)
        return cov + reg_covar * jnp.eye(d, dtype=xv.dtype)
    if cov_type == "diag":
        avg_x2 = resp.T @ (xv * xv) / nk[:, None]
        cov = avg_x2 - means * means
        return cov + reg_covar
    # spherical: mean of diag variances
    avg_x2 = resp.T @ (xv * xv) / nk[:, None]
    var = jnp.mean(avg_x2 - means * means, axis=1)
    return var + reg_covar


# `overrides` (the chunked/resumed EM parameter carries) is DONATED: XLA
# aliases weights/means/covs to their updated outputs and reuses the HBM
# in place across chunks; the (m, k) responsibilities never leave the
# device program at all (e_step -> m_step fuse inside the while_loop).
# Callers never reuse a passed overrides tuple afterwards.
@partial(_pjit, static_argnames=("shape", "cov_type", "max_iter"),
         donate_argnames=("overrides",), name="gm_fit")
@precise
def _gm_fit(xp, shape, resp0, cov_type, reg_covar, tol, max_iter,
            overrides=(None, None, None), prev_lb0=None):
    m, n = shape
    xv = xp[:, :n]
    xv = lax.with_sharding_constraint(xv, _mesh.row_sharding())
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m).astype(xv.dtype)

    def m_step(resp):
        resp = resp * w[:, None]
        nk = jnp.sum(resp, axis=0) + 1e-10                    # psum over rows
        means = resp.T @ xv / nk[:, None]                     # GEMM + psum
        covs = _estimate_covs(xv, resp, nk, means, cov_type, reg_covar, w)
        weights = nk / m
        return weights, means, covs

    weights0, means0, covs0 = m_step(resp0)
    w_o, mu_o, c_o = overrides
    weights0 = weights0 if w_o is None else w_o
    means0 = means0 if mu_o is None else mu_o
    covs0 = covs0 if c_o is None else c_o

    def e_step(weights, means, covs):
        prec = _chol_precisions(covs, cov_type, n)
        logp = _log_prob(xv, means, prec, cov_type, n) + jnp.log(weights)[None, :]
        lse = jax.scipy.special.logsumexp(logp, axis=1)
        resp = jnp.exp(logp - lse[:, None])
        ll = jnp.sum(lse * w) / m                             # mean log-likelihood
        return resp, ll

    def step(carry):
        weights, means, covs, prev_lb, _, it, hist = carry
        resp, lb = e_step(weights, means, covs)
        weights, means, covs = m_step(resp)
        conv = jnp.abs(lb - prev_lb) < tol
        return weights, means, covs, lb, conv, it + 1, hist.at[it].set(lb)

    def cond(carry):
        _, _, _, lb, conv, it, _ = carry
        return (~conv) & (it < max_iter)

    lb0 = jnp.asarray(-jnp.inf, xv.dtype) if prev_lb0 is None else \
        jnp.asarray(prev_lb0, xv.dtype)
    init = (weights0, means0, covs0, lb0, jnp.asarray(False), jnp.int32(0),
            jnp.zeros((max_iter,), xv.dtype))
    weights, means, covs, lb, conv, n_iter, hist = \
        lax.while_loop(cond, step, init)
    # fused health vector — same program, zero extra dispatches (the EM
    # lower bound is nondecreasing, so `hist` is the monotone signal)
    hvec = _health.health_vec(carries=(weights, means, covs), hist=hist,
                              n_done=n_iter, increasing=True)
    return weights, means, covs, lb, n_iter, conv, hist, hvec


@partial(_pjit, static_argnames=("shape", "cov_type"), name="gm_loglik")
@precise
def _gm_loglik(xp, shape, weights, means, covs, cov_type):
    m, n = shape
    xv = xp[:, :n]
    prec = _chol_precisions(covs, cov_type, n)
    logp = _log_prob(xv, means, prec, cov_type, n) + jnp.log(weights)[None, :]
    lse = jax.scipy.special.logsumexp(logp, axis=1)
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m).astype(xv.dtype)
    return jnp.sum(lse * w) / m


def _gm_predict_kernel(cfg, xp, weights, means, covs):
    """`predict` as a fusion-node body (cfg = (shape, cov_type))."""
    shape, cov_type = cfg
    m, n = shape
    xv = xp[:, :n]
    prec = _chol_precisions(covs, cov_type, n)
    logp = _log_prob(xv, means, prec, cov_type, n) + jnp.log(weights)[None, :]
    # component ids stay int32 (float32 is exact only below 2^24)
    labels = jnp.argmax(logp, axis=1).astype(jnp.int32)
    valid = lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m
    return jnp.where(valid, labels, 0)[:, None]
