"""Mini-batch K-means with a streaming ``partial_fit`` (round-12; the
first streaming estimator of ROADMAP item 3).

This module is the living proof of the :mod:`dislib_tpu.runtime.fitloop`
recipe: it contains ZERO bespoke resilience code (lint-enforced by
``tests/test_health_guard_lint.py``) yet passes the same rollback /
watchdog / preemption / quarantine fault grid as the seven ported
chunked estimators — every resilience behavior is the driver's.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.cluster.kmeans import KMeans
from dislib_tpu.data.array import Array, array as _ds_array, \
    ensure_canonical as _ensure_canonical
from dislib_tpu.ops import distances_sq as _distances_sq
from dislib_tpu.ops.base import precise
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health
from dislib_tpu.utils.profiling import profiled_jit as _pjit


class MiniBatchKMeans(KMeans):
    """Mini-batch K-means with a streaming ``partial_fit`` — the first
    streaming estimator of ROADMAP item 3, and the acceptance test for
    the :class:`~dislib_tpu.runtime.ChunkedFitLoop` recipe: this class
    contains ZERO bespoke resilience code (lint-enforced).  Rollback to
    last-good, the chunk watchdog, the escalation ladder, verdict-gated
    snapshots, and preemption polling all come from the driver — a batch
    that trips a guard is rolled back and re-run, a hung batch becomes a
    typed ``WatchdogTimeout``, and a preemption notice lands as a clean
    ``Preempted`` between batches with the stream resumable from the
    snapshot.

    Each ``partial_fit(batch)`` is ONE fused dispatch (assign +
    per-center batch mass/means + online center update + health vector).
    ``counts_`` carries the accumulated per-center sample mass, so the
    update is the standard  c_j ← c_j + (m_j/counts_j)·(mean_j − c_j)
    with the learning rate decaying as mass accumulates (Sculley 2010).
    ``fit`` is a convenience wrapper streaming row slices of a ds-array
    through ``partial_fit`` for ``epochs`` passes.

    Parameters
    ----------
    n_clusters : int, default 8
    init : 'random' or ndarray (n_clusters, n_features) — fresh centers
        come from the FIRST batch's rows under 'random'.
    batch_size : int, default 256 — row slice width used by ``fit``.
    epochs : int, default 1 — passes over the data in ``fit``.
    random_state : int or None

    Attributes
    ----------
    centers_ : ndarray (n_clusters, n_features)
    counts_ : ndarray (n_clusters,) — per-center accumulated sample mass.
    n_batches_ : int — batches consumed by the stream so far.
    inertia_ : float — the last batch's within-cluster sum of squares.
    """

    def __init__(self, n_clusters=8, init="random", batch_size=256,
                 epochs=1, random_state=None, verbose=False):
        self.n_clusters = n_clusters
        self.init = init
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.random_state = random_state
        self.verbose = verbose
        self._loop = None

    # searches run the plain synchronous fallback — the KMeans async-trial
    # kernels would silently swap full-batch Lloyd's in for the mini-batch
    # update
    _fit_async = BaseEstimator._fit_async
    _fit_finalize = BaseEstimator._fit_finalize
    _score_async = BaseEstimator._score_async

    def partial_fit(self, x, y=None, checkpoint=None, health=None):
        """Consume one batch (ds-array or host ndarray).  The first call
        configures the stream's resilience (``checkpoint``/``health``
        are stream-wide; later calls reuse them) and restores from the
        checkpoint if one exists — a preempted stream resumes where it
        snapshot."""
        xb = x if isinstance(x, Array) else \
            _ds_array(np.asarray(x, np.float32))
        if self._loop is None:
            # the batch holder persists WITH the loop: the elastic tier's
            # rebind hook must re-lay out whichever batch is current when
            # a mid-stream mesh shrink lands, not the first call's
            self._batch = {}
            self._loop = _fitloop.ChunkedFitLoop(
                "minibatch_kmeans", checkpoint=checkpoint, health=health,
                carry_names=("centers", "counts"),
                carry_shapes=((self.n_clusters, xb.shape[1]),
                              (self.n_clusters,)),
                save_every=checkpoint.every if checkpoint is not None else 1,
                elastic=_fitloop.data_rebind(self._batch))
        batch, loop = self._batch, self._loop
        # after a mid-stream elastic shrink, batches the producer built
        # under the pre-shrink mesh re-lay out on device at ingest
        batch["x"] = xb if loop.info["mesh_shrinks"] == 0 \
            else _ensure_canonical(xb)
        # re-declared per batch: THIS batch's width defines what a
        # compatible snapshot looks like (the rollback funnel judges it)
        loop.snapshot_expect = {"centers": (self.n_clusters, xb.shape[1]),
                                "counts": (self.n_clusters,)}

        def init(rem):
            centers = jnp.asarray(
                rem.perturb(self._init_centers(batch["x"])))
            return _fitloop.LoopState(
                (centers, jnp.zeros((self.n_clusters,), jnp.float32)))

        def restore(snap, rem):
            # centers/counts compatibility is declared via
            # loop.snapshot_expect and judged by the rollback funnel
            centers = np.asarray(snap["centers"])
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(centers)),
                 jnp.asarray(rem.perturb(snap["counts"]))),
                it=int(snap["n_batches"]))

        def step(st, chunk):
            xd = batch["x"]
            centers, counts, inertia, hvec = _mbk_step(
                xd._data, xd.shape, *st.carries)
            # state/history deferred: the watchdogged hvec read stays the
            # batch's first force point
            return _fitloop.ChunkOutcome(
                lambda: _fitloop.LoopState((centers, counts), st.it + 1,
                                           extra=inertia),
                hvec=hvec, history=lambda: (float(inertia),))

        def snapshot(st):
            return {"centers": _fetch(st.carries[0], blocking=False),
                    "counts": _fetch(st.carries[1], blocking=False),
                    "n_batches": st.it, "inertia": float(st.extra)}

        st = loop.run_one(init=init, step=step, restore=restore,
                          snapshot=snapshot)
        self.centers_ = np.asarray(jax.device_get(st.carries[0]))
        self.counts_ = np.asarray(jax.device_get(st.carries[1]))
        self.n_batches_ = self.n_iter_ = st.it
        self.inertia_ = float(st.extra)
        self.history_ = np.asarray(loop.history, dtype=np.float64)
        self.fit_info_ = loop.info
        return self

    def fit(self, x: Array, y=None, checkpoint=None, health=None):
        """Stream ``x`` through ``partial_fit`` in ``batch_size`` row
        slices, ``epochs`` passes.  Restarts the stream state (a fresh
        ``fit`` is a fresh model; ``partial_fit`` is the continuation
        API) — EXCEPT when ``checkpoint`` already holds a snapshot: the
        fit then resumes the stream at the recorded batch position (the
        preemption-recovery re-run), never re-consuming batches the
        snapshot already contains, and lands on the uninterrupted run's
        model."""
        self._loop = None
        start, snap = _fitloop.stream_state(checkpoint)
        m = x.shape[0]
        mesh = _mesh.get_mesh()
        g = 0                           # global batch index across epochs
        for _ in range(max(1, self.epochs)):
            for s in range(0, m, self.batch_size):
                g += 1
                if g <= start:
                    continue            # already consumed by the snapshot
                if _mesh.get_mesh() is not mesh:
                    # an elastic mesh-shrink landed mid-stream: re-lay the
                    # source out for the surviving devices before slicing
                    # the next batch from it
                    x, mesh = _ensure_canonical(x), _mesh.get_mesh()
                self.partial_fit(x[s: min(s + self.batch_size, m), :],
                                 checkpoint=checkpoint, health=health)
        if start and g <= start:
            # the snapshot already covers the whole stream (a completed
            # fit re-run): adopt the fitted state without re-dispatching
            self.centers_ = np.asarray(snap["centers"])
            self.counts_ = np.asarray(snap["counts"])
            self.n_batches_ = self.n_iter_ = int(snap["n_batches"])
            self.inertia_ = float(snap.get("inertia", np.nan))
            self.history_ = np.asarray([], dtype=np.float64)
            self.fit_info_ = {"chunks": 0, "rollbacks": 0,
                              "mesh_shrinks": 0, "escalations": {}}
        elif checkpoint is not None:
            # run()'s contract for the streaming path: the final snapshot
            # is on disk before fit returns (run_one never flushes — the
            # stream owner does)
            checkpoint.flush()
        return self


@partial(_pjit, static_argnames=("shape",), name="mbkmeans_step")
@precise
def _mbk_step(xp, shape, centers, counts):
    """One mini-batch update — assign, per-center batch mass/means, online
    center update, fused health vector: the whole ``partial_fit`` chunk is
    this ONE dispatch (counter-asserted in ``tests/test_minibatch``)."""
    m, n = shape
    xv = xp[:, :n]
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0],), 0) < m) \
        .astype(xv.dtype)
    d = _distances_sq(xv, centers)
    labels = jnp.argmin(d, axis=1)
    onehot = jax.nn.one_hot(labels, centers.shape[0], dtype=xv.dtype) \
        * w[:, None]
    bc = jnp.sum(onehot, axis=0)                      # (k,) batch mass
    bmean = (onehot.T @ xv) / jnp.maximum(bc, 1.0)[:, None]
    new_counts = counts + bc
    eta = (bc / jnp.maximum(new_counts, 1.0))[:, None]
    new_centers = jnp.where(bc[:, None] > 0,
                            centers + eta * (bmean - centers), centers)
    inertia = jnp.sum(jnp.min(d, axis=1) * w)
    # NO loss history in the health vector: consecutive chunks see
    # DIFFERENT batches, so batch-to-batch inertia is not a monotone
    # trajectory — feeding it to the cross-chunk monotone guard would
    # false-trip an armed `monotone_rtol` on healthy streams
    # (review-found).  Non-finite batches/centers stay covered by the
    # inputs/carries slots.
    hvec = _health.health_vec(carries=(new_centers, new_counts),
                              inputs=(xv,))
    return new_centers, new_counts, inertia, hvec
