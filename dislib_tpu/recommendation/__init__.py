"""Recommendation (reference: `dislib/recommendation` — ALS; SURVEY.md §3.3)."""

from dislib_tpu.recommendation.als import ALS

__all__ = ["ALS"]
