"""ALS collaborative filtering (reference: `dislib/recommendation/als` —
`_update_chunk` tasks solving per-row regularized least squares alternately
for user and item factors on a blocked sparse ratings matrix, RMSE-based
convergence; SURVEY.md §3.3).

TPU-native redesign:

- The reference alternates over the two matrix dimensions by mapping
  `_update_chunk` tasks over row blocks of R (user step) and of Rᵀ (item
  step).  Here BOTH half-steps live inside ONE jitted `lax.while_loop`
  iteration over the sharded ratings matrix: the per-user normal equations
  ``A_u = Σ_{j∈Ω_u} v_j v_jᵀ + λ n_u I`` are built for *all* users at once as
  one GEMM (``mask @ (v_f · v_g)`` reshaped to (m, f, f)) plus ``b = R @ V``
  — MXU-bound — followed by a batched Cholesky solve.  The item step is the
  same kernel on the transpose.
- Dense `Array` ratings are dense-with-mask (SURVEY §8 "Sparse support"
  fallback): entry==0 means unobserved, exactly the information the
  reference's CSR sparsity structure carries.  The ds-array padding region
  is zero by invariant, so padded rows/cols solve to λI·x=0 → zero factors
  and never perturb the observed entries.
- `SparseArray` ratings take a TRUE sparse path (`_als_fit_sparse`): the
  normal equations are segment-sums over the observed (user, item, rating)
  triplets — O(nnz·f²) work/memory, no densification — matching the
  reference's CSR-block `_update_chunk` economics.
- Convergence (|ΔRMSE| < tol, on train or held-out test ratings) is decided
  ON DEVICE inside the while_loop — host syncs once per fit, not per
  iteration (the reference syncs the RMSE scalar every iteration).
- Regularisation follows the reference's Zhou et al. weighted-λ scheme:
  λ · n_u scales with each row's observation count.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, \
    ensure_canonical as _ensure_canonical
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise
from dislib_tpu.runtime import fetch as _fetch, repad_rows as _repad_rows
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health
from dislib_tpu.utils.dlog import verbose_logger
from dislib_tpu.utils.profiling import profiled_jit as _pjit


class ALS(BaseEstimator):
    """Alternating Least Squares matrix factorisation.

    Parameters (reference parity: `dislib/recommendation/als :: ALS`)
    ----------
    n_f : int, default 8
        Number of latent factors.
    lambda_ : float, default 0.065
        Regularisation strength (weighted by per-row rating counts).
    tol : float, default 1e-4
        Convergence threshold on |ΔRMSE| between iterations.
    max_iter : int, default 100
    random_state : int or None
    verbose : bool — log per-chunk RMSE under the dslib.als logger.
    arity : int — accepted and ignored (reference reduction-tree fan-in;
        reduction topology is XLA's job now).

    Attributes
    ----------
    users_ : ndarray (n_users, n_f) — user factor matrix U.
    items_ : ndarray (n_items, n_f) — item factor matrix V.
    converged_ : bool
    n_iter_ : int
    rmse_ : float — RMSE over the convergence ratings at the last iteration.
    history_ : ndarray (n_iter_,) — per-iteration held-out RMSE (SURVEY §6).
    """

    def __init__(self, n_f=8, lambda_=0.065, tol=1e-4, max_iter=100,
                 random_state=None, verbose=False, arity=48):
        self.n_f = n_f
        self.lambda_ = lambda_
        self.tol = tol
        self.max_iter = max_iter
        self.random_state = random_state
        self.verbose = verbose
        self.arity = arity

    def fit(self, x: Array, test=None, checkpoint=None, health=None):
        """Factorise the ratings matrix ``x`` (users × items, 0 = unobserved).

        ``test`` — optional held-out ratings (ndarray or ds-array with the
        same shape, 0 = unobserved) used for the convergence RMSE instead of
        the training ratings, as in the reference.
        ``checkpoint`` — optional ``FitCheckpoint``: run in `every`-iteration
        chunks, snapshot (users, items, rmse, n_iter) after each, resume from
        the snapshot on re-run (SURVEY §6 checkpoint/resume).  Between
        chunks the loop honours the preemption flag (`dislib_tpu.runtime`):
        snapshot first, then a clean ``Preempted``.  Snapshots record the
        LOGICAL factor dims, so a checkpoint written on one mesh resumes on
        a different device count (the factors are re-padded on restore —
        elastic resume).
        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`;
        each chunk's kernel emits a fused health vector over the factors
        and the RMSE history.  A tripped guard rolls back to the
        last-good snapshot; the ``halve`` action additionally doubles
        ``lambda_`` per restart (the normal-equation ridge — ALS's
        damping knob against ill-conditioned solves).
        """
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        from dislib_tpu.data.sparse import SparseArray
        sparse_in = isinstance(x, SparseArray)
        t_host = None
        if not sparse_in and test is not None:
            import scipy.sparse as sp
            if isinstance(test, SparseArray):
                t_host = np.asarray(test.collect().toarray())
            else:
                t = test.collect() if isinstance(test, Array) else test
                t_host = np.asarray(t.toarray() if sp.issparse(t) else t)
            if t_host.shape != x.shape:
                raise ValueError(f"test ratings shape {t_host.shape} != "
                                 f"ratings shape {x.shape}")
        seed = self.random_state if self.random_state is not None else 0
        box = {"x": x, "lam": float(self.lambda_), "rmse": np.inf}

        def _bind_test():
            if sparse_in:
                # true sparse path: row-panel-sharded buffers for the
                # ratings AND the held-out test entries — O(nnz) storage,
                # no densification ever happens
                box["rep"] = box["x"].sharded()
                if "t_sa" not in box:
                    box["t_sa"] = None if test is None \
                        else _test_sparse(test, x.shape)
                box["trep"] = box["rep"] if box["t_sa"] is None \
                    else box["t_sa"].sharded()
            else:
                box["test_p"] = box["x"]._data if t_host is None \
                    else _pad_like(t_host, box["x"])
        _bind_test()

        def rebind(mesh):
            if mesh is None:            # pre-switch: force pending chains
                if not sparse_in:
                    box["x"].force()
                return
            if not sparse_in:
                box["x"] = _ensure_canonical(box["x"])
            _bind_test()                # sparse: reps reshard ON DEVICE
                                        # through the sparse rechunk router

        log = verbose_logger("als", self.verbose)
        loop = _fitloop.ChunkedFitLoop(
            "als", checkpoint=checkpoint, health=health,
            max_iter=self.max_iter, carry_names=("users", "items"),
            carry_shapes=((x.shape[0], int(self.n_f)),
                          (x.shape[1], int(self.n_f))),
            # snapshots carry the LOGICAL factor dims (m, n) as scalars;
            # the stored factor ROWS may be padded for a different mesh
            # (elastic resume re-pads), so only the factor width is pinned
            snapshot_expect={"m": int(x.shape[0]), "n": int(x.shape[1]),
                             "users": (None, int(self.n_f)),
                             "items": (None, int(self.n_f))},
            elastic=rebind)

        def init(rem):
            # ALS damping: the 'halve' tier raises the per-row ridge λ·n_u
            # per attempt (ill-conditioned normal equations are the
            # numeric failure mode of the batched Cholesky solves)
            box["lam"] = float(self.lambda_) * rem.damping
            box["rmse"] = np.inf
            return _fitloop.LoopState(())   # fresh: the kernel seeds itself

        def restore(snap, rem):
            # snapshot compatibility (logical dims + factor width) is
            # declared via snapshot_expect and judged by the rollback
            # funnel; elastic resume re-pads the factor rows for THIS
            # mesh (runtime.repad_rows)
            sm, sn = int(snap["m"]), int(snap["n"])
            box["lam"] = float(self.lambda_) * rem.damping
            box["rmse"] = float(snap["rmse"])
            if sparse_in:
                # the sharded kernel carries U padded to the CURRENT
                # mesh's row quantum and V at its logical length
                from dislib_tpu.data.sparse import _padded_rows
                tu = _padded_rows(x.shape[0], _mesh.get_mesh())
                tv = x.shape[1]
            else:
                tu = box["x"]._data.shape[0]
                tv = box["x"]._data.shape[1]
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(_repad_rows(snap["users"], sm, tu))),
                 jnp.asarray(rem.perturb(_repad_rows(snap["items"], sn, tv)))),
                it=int(snap["n_iter"]),
                done=bool(snap.get("converged", False)),
                extra=float(snap["rmse"]))

        def step(st, chunk):
            state = (*st.carries, st.extra) if st.carries else None
            if sparse_in:
                rep, trep = box["rep"], box["trep"]
                u, v, rmse_dev, n_done, conv, hist, hvec = _als_fit_sparse(
                    rep.data, rep.lrows, rep.cols, rep.counts_dev,
                    trep.data, trep.lrows, trep.cols, trep.counts_dev,
                    x.shape[0], x.shape[1],
                    int(self.n_f), box["lam"], float(self.tol),
                    chunk, int(seed), _mesh.get_mesh(), init_state=state)
            else:
                u, v, rmse_dev, n_done, conv, hist, hvec = _als_fit(
                    box["x"]._data, box["test_p"], x.shape, int(self.n_f),
                    box["lam"], float(self.tol), chunk, int(seed),
                    init_state=state)

            def commit():
                # deferred scalar syncs: the watchdogged hvec read stays
                # the chunk's first force point
                box["rmse"] = float(rmse_dev)
                it = st.it + int(n_done)
                log.info("iter %d: rmse=%.6g", it, box["rmse"])
                return _fitloop.LoopState((u, v), it, bool(conv),
                                          extra=box["rmse"])

            return _fitloop.ChunkOutcome(
                commit, hvec=hvec,
                history=lambda: _fetch(hist)[: int(n_done)])

        def snapshot(st):
            # the factors are DONATED to the next chunk's kernel call
            # (their HBM is reused in place), so their device->host copies
            # must land before that dispatch: fetch blocking, and offload
            # only the checksum+write to the snapshot worker
            return {"users": _fetch(st.carries[0]),
                    "items": _fetch(st.carries[1]),
                    "m": x.shape[0], "n": x.shape[1],
                    "rmse": st.extra, "n_iter": st.it, "converged": st.done}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        u, v = st.carries
        m, n = x.shape
        self.users_ = np.asarray(jax.device_get(u))[:m]
        self.items_ = np.asarray(jax.device_get(v))[:n]
        self.rmse_ = float(box["rmse"])
        self.n_iter_ = st.it
        self.converged_ = st.done
        self.history_ = np.asarray(loop.history, dtype=np.float64)
        self.fit_info_ = loop.info
        return self

    # async trial protocol (SURVEY §4.5): the no-test, no-checkpoint fit is
    # one jitted while_loop; the handle is its device output tuple.  Sparse
    # inputs read their triplets (input prep, not fit results) at dispatch.
    def _fit_async(self, x, y=None):
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        from dislib_tpu.data.sparse import SparseArray
        seed = self.random_state if self.random_state is not None else 0
        if isinstance(x, SparseArray):
            rep = x.sharded()
            bufs = (rep.data, rep.lrows, rep.cols, rep.counts_dev)
            out = _als_fit_sparse(*bufs, *bufs,
                                  x.shape[0], x.shape[1], int(self.n_f),
                                  float(self.lambda_), float(self.tol),
                                  self.max_iter, int(seed),
                                  _mesh.get_mesh())
        else:
            out = _als_fit(x._data, x._data, x.shape, int(self.n_f),
                           float(self.lambda_), float(self.tol),
                           self.max_iter, int(seed))
        return (out, x.shape)

    def _fit_finalize(self, state):
        if state is None:
            return
        (u, v, rmse, n_iter, conv, hist, _), (m, n) = state
        self.users_ = np.asarray(jax.device_get(u))[:m]
        self.items_ = np.asarray(jax.device_get(v))[:n]
        self.rmse_ = float(rmse)
        self.n_iter_ = int(n_iter)
        self.converged_ = bool(conv)
        self.history_ = np.asarray(
            jax.device_get(hist), dtype=np.float64)[: self.n_iter_]

    def predict_user(self, user_id: int) -> np.ndarray:
        """Predicted ratings for every item for one user (reference parity)."""
        self._check_fitted()
        if not 0 <= user_id < self.users_.shape[0]:
            raise IndexError(f"user_id {user_id} out of range")
        return self.users_[user_id] @ self.items_.T

    def fold_in(self, ratings, top_n=None):
        """Score BRAND-NEW users against the trained item factors with no
        refit — the core recommendation-at-scale operation (ROADMAP item
        1's online fold-in): solve each new user's regularized normal
        equations ``(Σ_{j∈Ω} v_j v_jᵀ + λ n I) u = Σ_j r_j v_j`` against
        the FROZEN ``items_`` and return predicted ratings for every
        item, all in ONE fused dispatch (solve + predict GEMM; the item
        factors are device-cached across calls via the serving-layer
        leaf cache, so a warm fold-in re-transfers nothing).

        ``ratings``: one user's ratings or a (k, n_items) batch —
        SparseArray, scipy sparse, ndarray (0 = unobserved), or a
        pre-padded device pair ``(cols, vals)`` of shape (k, s) with
        (column 0, value 0) pads — the zero-host-transfer serving form.

        ``top_n`` — when set, rank inside the SAME dispatch
        (``lax.top_k`` fuses after the predict GEMM) and return the
        ``(item_ids, scores)`` pair of (k, top_n) ndarrays instead of the
        full score matrix: the host fetch shrinks from n_items to top_n
        per user and no host-side argsort follows.

        Returns the (k, n_items) predicted-ratings ndarray, or the
        ``(item_ids, scores)`` pair with ``top_n``."""
        out = self._fold_in_device(ratings, top_n=top_n)
        if top_n is not None:
            ids, scores = out
            return np.asarray(_fetch(ids)), np.asarray(_fetch(scores))
        return np.asarray(_fetch(out))

    def _fold_in_device(self, ratings, precision=None, top_n=None):
        """The device half of :meth:`fold_in`: returns the predictions
        as a device array, unfetched — what the sparse serving pipeline
        consumes (its response fetch is the one blessed sync)."""
        self._check_fitted()
        from dislib_tpu.ops import precision as _px
        if isinstance(ratings, tuple) and len(ratings) == 2:
            cols, vals = (jnp.asarray(a) for a in ratings)
            if not jnp.issubdtype(cols.dtype, jnp.integer):
                # the serving encoding carries ids as float32 (exact
                # below 2^24) — the gather needs integer indices
                cols = cols.astype(jnp.int32)
        else:
            cols, vals = _fold_in_pack(ratings, self.items_.shape[0])
        if cols.ndim == 1:
            cols, vals = cols[None, :], vals[None, :]
        (items,) = self._predict_leaves(self.items_)
        _, preds = _als_fold_in(vals, cols, items, float(self.lambda_),
                                int(self.n_f), _px.resolve(precision),
                                top_n=int(top_n or 0))
        return preds

    def _check_fitted(self):
        if not hasattr(self, "users_"):
            raise RuntimeError("ALS is not fitted")


def _test_sparse(test, want_shape):
    """Held-out ratings → a SparseArray (0 = unobserved) whose sharded
    buffers feed the fit kernel; accepts SparseArray, scipy sparse,
    ds-array, or ndarray without ever densifying a sparse input."""
    from dislib_tpu.data.sparse import SparseArray
    import scipy.sparse as sp
    t = test
    if isinstance(t, Array) and not isinstance(t, SparseArray):
        t = t.collect()
    if not (isinstance(t, SparseArray) or sp.issparse(t)):
        t = sp.csr_matrix(np.asarray(t, np.float32))
    if tuple(t.shape) != tuple(want_shape):
        raise ValueError(f"test ratings shape {tuple(t.shape)} != "
                         f"ratings shape {tuple(want_shape)}")
    if isinstance(t, SparseArray):
        return t
    return SparseArray.from_scipy(t)


def _fold_in_pack(ratings, n_items):
    """Host packing of new-user ratings into padded (cols, vals) device
    pairs — per-user nse = the batch's densest row (quantized up), pads
    at (column 0, value 0) so they are additive no-ops in the fold-in
    normal equations (the library pad discipline)."""
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray, nse_quantum
    t = ratings
    if isinstance(t, SparseArray):
        t = t.collect()
    if not sp.issparse(t):
        t = sp.csr_matrix(np.atleast_2d(np.asarray(t, np.float32)))
    t = t.tocsr()
    if t.shape[1] != n_items:
        raise ValueError(f"fold_in ratings have {t.shape[1]} items, the "
                         f"model was trained on {n_items}")
    k = t.shape[0]
    row_nnz = np.diff(t.indptr)
    q = nse_quantum()
    s = int(math.ceil(max(int(row_nnz.max(initial=1)), 1) / q) * q)
    cols = np.zeros((k, s), np.int32)
    vals = np.zeros((k, s), np.float32)
    for i in range(k):
        lo, hi = t.indptr[i], t.indptr[i + 1]
        cols[i, : hi - lo] = t.indices[lo:hi]
        vals[i, : hi - lo] = t.data[lo:hi]
    return jnp.asarray(cols), jnp.asarray(vals)


def _pad_like(t: np.ndarray, x: Array):
    """Pad host ratings to x's padded device shape (zeros outside logical)."""
    out = np.zeros(x._data.shape, dtype=x._data.dtype)
    out[: t.shape[0], : t.shape[1]] = t
    return jnp.asarray(out)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _solve_factors(r, mask, v, lambda_, n_f):
    """Per-row regularized LS for all rows at once (the `_update_chunk` role).

    A = einsum('mn,nf,ng->mfg', mask, v, v) — XLA lowers this to one GEMM
    ``mask @ (v ⊗ v)`` of shape (m, n)×(n, f²); b = r @ v is a second GEMM.
    Batched Cholesky solve finishes the normal equations.
    """
    counts = jnp.sum(mask, axis=1)
    b = r @ v                                            # (m, f)
    vv = (v[:, :, None] * v[:, None, :]).reshape(v.shape[0], n_f * n_f)
    a = (mask @ vv).reshape(-1, n_f, n_f)
    reg = lambda_ * jnp.maximum(counts, 1.0)
    a = a + reg[:, None, None] * jnp.eye(n_f, dtype=r.dtype)
    chol = jax.scipy.linalg.cho_factor(a)
    return jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]


# init_state (the resumed/chunked factor carries) is DONATED: XLA aliases
# u0/v0 to the output factors and reuses their HBM in place instead of
# double-buffering the two largest arrays of the fit (round-7 perf PR).
# Callers never reuse a passed init_state afterwards.
@partial(_pjit, static_argnames=("shape", "n_f", "max_iter"),
         donate_argnames=("init_state",), name="als_fit")
@precise
def _als_fit(rp, test_p, shape, n_f, lambda_, tol, max_iter, seed,
             init_state=None):
    rp = lax.with_sharding_constraint(rp, _mesh.data_sharding())
    mask = (rp != 0).astype(rp.dtype)
    tmask = (test_p != 0).astype(rp.dtype)
    key = jax.random.PRNGKey(seed)
    ku, kv = jax.random.split(key)
    # reference seeds item factors from the per-item mean rating; uniform
    # init scaled to the mean magnitude behaves equivalently
    u0 = jax.random.uniform(ku, (rp.shape[0], n_f), rp.dtype)
    v0 = jax.random.uniform(kv, (rp.shape[1], n_f), rp.dtype)
    prev0 = jnp.asarray(jnp.inf, rp.dtype)
    if init_state is not None:                 # mid-fit checkpoint resume
        u0, v0, prev0 = init_state
        prev0 = jnp.asarray(prev0, rp.dtype)

    def rmse(u, v):
        se = ((u @ v.T - test_p) * tmask) ** 2
        return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(tmask), 1.0))

    def step(carry):
        u, v, prev_rmse, it, _, hist = carry
        u = _solve_factors(rp, mask, v, lambda_, n_f)
        v = _solve_factors(rp.T, mask.T, u, lambda_, n_f)
        cur = rmse(u, v)
        conv = jnp.abs(prev_rmse - cur) < tol
        return u, v, cur, it + 1, conv, hist.at[it].set(cur)

    def cond(carry):
        _, _, _, it, conv, _ = carry
        return (it < max_iter) & (~conv)

    init = (u0, v0, prev0, jnp.int32(0), jnp.asarray(False),
            jnp.zeros((max_iter,), rp.dtype))
    u, v, cur, n_iter, conv, hist = lax.while_loop(cond, step, init)
    # fused health vector — same program, zero extra dispatches
    from dislib_tpu.runtime import health as _health
    hvec = _health.health_vec(carries=(u, v), hist=hist, n_done=n_iter)
    return u, v, cur, n_iter, conv, hist, hvec


@partial(_pjit, static_argnames=("m", "n", "n_f", "max_iter", "mesh"),
         donate_argnames=("init_state",), name="als_fit_sparse")
@precise
def _als_fit_sparse(data, lrows, cols, counts, tdata, tlrows, tcols, tcounts,
                    m, n, n_f, lambda_, tol, max_iter, seed, mesh,
                    init_state=None):
    """Sharded sparse ALS: ONE jitted ``shard_map`` over the row-sharded
    :class:`~dislib_tpu.data.sparse.ShardedSparse` ratings buffers, the
    whole while_loop inside (round-14 sparse PR — the fit rides the same
    machinery as the SpMM fast path instead of the old replicated
    single-program kernel).

    DrJAX's per-shard-update + cross-shard-reduce decomposition
    (arXiv:2403.07128), literally: the USER half-step is fully
    shard-local (each shard owns its users' entries, so their normal
    equations — segment-sums of v_j v_jᵀ outer products streamed over nse
    chunks, O(chunk·f²) peak — never leave the shard; U stays row-sharded
    for the whole fit), and the ITEM half-step is a shard-local partial
    A_i/b_i plus ONE ``psum`` over the rows axis (V is the replicated
    small factor).  The convergence RMSE reduces the same way.  Per-shard
    memory is O(nnz/p · f) + O(n·f²) — the factors of the paper-scale
    recommender shard with the data.

    Entry weights are ``(slot < count) & (value != 0)``: 0 = unobserved
    (the dense-with-mask semantics) AND the nse pads — even poisoned
    ones — carry weight zero (the slot mask, defense in depth over the
    zero-value sentinel-column pad discipline)."""
    p = mesh.shape[_mesh.ROWS]
    from dislib_tpu.data.sparse import _padded_rows
    m_local = _padded_rows(m, mesh) // p
    nse = data.shape[1]
    nse_t = tdata.shape[1]
    chunk = max(1, min(nse, _SPARSE_CHUNK, _SPARSE_BUDGET // (n_f * n_f)))
    n_chunks = -(-nse // chunk)
    pad = n_chunks * chunk - nse

    def shard_fn(d_s, lr_s, cc_s, cnt_s, td_s, tlr_s, tcc_s, tcnt_s, u0_s,
                 v0_r, prev_r):
        d_e, lr, cc, cnt = d_s[0], lr_s[0], cc_s[0], cnt_s[0]
        td, tlr, tcc, tcnt = td_s[0], tlr_s[0], tcc_s[0], tcnt_s[0]
        slot_ok = lax.broadcasted_iota(jnp.int32, (nse,), 0) < cnt
        w = (slot_ok & (d_e != 0)).astype(d_e.dtype)
        # chunk-pad the entry stream (pads carry weight 0 → additive no-op)
        d_p = jnp.pad(d_e * w, (0, pad))
        lr_p = jnp.pad(lr, (0, pad))
        cc_p = jnp.pad(cc, (0, pad))
        w_p = jnp.pad(w, (0, pad))
        tok = lax.broadcasted_iota(jnp.int32, (nse_t,), 0) < tcnt
        tw = (tok & (td != 0)).astype(d_e.dtype)
        eye = jnp.eye(n_f, dtype=d_e.dtype)

        def solve(seg_c, other, idx_c, nseg, reduce_rows):
            """Normal equations streamed over nse chunks; the item step
            (``reduce_rows``) combines per-shard partials with one psum."""

            def body(acc, cx):
                sc, ic, vc, wc = cx
                g = other[ic] * wc[:, None]           # pad rows → all-zero
                b = jax.ops.segment_sum(vc[:, None] * g, sc,
                                        num_segments=nseg)
                outer = (g[:, :, None] * g[:, None, :]) \
                    .reshape(chunk, n_f * n_f)
                a = jax.ops.segment_sum(outer, sc, num_segments=nseg)
                cnt_ = jax.ops.segment_sum(wc, sc, num_segments=nseg)
                return (acc[0] + a, acc[1] + b, acc[2] + cnt_), None

            acc0 = (jnp.zeros((nseg, n_f * n_f), d_e.dtype),
                    jnp.zeros((nseg, n_f), d_e.dtype),
                    jnp.zeros((nseg,), d_e.dtype))
            (a, b, cnts), _ = lax.scan(
                body, acc0,
                (seg_c.reshape(n_chunks, chunk),
                 idx_c.reshape(n_chunks, chunk),
                 d_p.reshape(n_chunks, chunk),
                 w_p.reshape(n_chunks, chunk)))
            if reduce_rows:               # cross-shard reduce: the ONE psum
                a = lax.psum(a, _mesh.ROWS)
                b = lax.psum(b, _mesh.ROWS)
                cnts = lax.psum(cnts, _mesh.ROWS)
            a = a.reshape(nseg, n_f, n_f)
            # unobserved rows: A = λ·I, b = 0 → zero factors (harmless)
            reg = lambda_ * jnp.maximum(cnts, 1.0)
            a = a + reg[:, None, None] * eye
            chol = jax.scipy.linalg.cho_factor(a)
            return jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]

        def rmse(u, v):
            pred = jnp.sum(u[tlr] * v[tcc], axis=1)
            se = lax.psum(jnp.sum(tw * (pred - td) ** 2), _mesh.ROWS)
            cnt_t = lax.psum(jnp.sum(tw), _mesh.ROWS)
            return jnp.sqrt(se / jnp.maximum(cnt_t, 1.0))

        def step(carry):
            u, v, prev_rmse, it, _, hist = carry
            u = solve(lr_p, v, cc_p, m_local, False)   # users: shard-local
            v = solve(cc_p, u, lr_p, n, True)          # items: psum-reduced
            cur = rmse(u, v)
            conv = jnp.abs(prev_rmse - cur) < tol
            return u, v, cur, it + 1, conv, hist.at[it].set(cur)

        def cond(carry):
            _, _, _, it, conv, _ = carry
            return (it < max_iter) & (~conv)

        if u0_s is None:
            key = jax.random.PRNGKey(seed)
            ku, kv = jax.random.split(key)
            ku = jax.random.fold_in(ku, lax.axis_index(_mesh.ROWS))
            u0 = jax.random.uniform(ku, (m_local, n_f), d_e.dtype)
            v0 = jax.random.uniform(kv, (n, n_f), d_e.dtype)
            prev0 = jnp.asarray(jnp.inf, d_e.dtype)
        else:
            u0 = u0_s
            v0 = v0_r
            prev0 = jnp.asarray(prev_r, d_e.dtype)
        # vma: a fresh u0 is rows-varying via the fold_in of axis_index;
        # v0/prev0 are replicated (same key / same scalar on every rank)
        init = (u0, v0, prev0, jnp.int32(0), jnp.asarray(False),
                jnp.zeros((max_iter,), d_e.dtype))
        u, v, cur, n_iter, conv, hist = lax.while_loop(cond, step, init)
        return u, v, cur, n_iter, conv, hist

    from jax.sharding import PartitionSpec as P
    row_spec = (P(_mesh.ROWS),) * 4
    if init_state is None:
        extra_specs = ()
        args = ()
    else:
        u0, v0, prev0 = init_state
        extra_specs = (P(_mesh.ROWS), P(), P())
        args = (u0, v0, jnp.asarray(prev0))

    def wrapper(*ops):
        if init_state is None:
            return shard_fn(*ops, None, None, None)
        return shard_fn(*ops)

    u, v, cur, n_iter, conv, hist = jax.shard_map(
        wrapper, mesh=mesh,
        in_specs=row_spec + row_spec + extra_specs,
        out_specs=(P(_mesh.ROWS), P(), P(), P(), P(), P()),
        check_vma=True,
    )(data, lrows, cols, counts, tdata, tlrows, tcols, tcounts, *args)
    # fused health vector — same program, zero extra dispatches
    from dislib_tpu.runtime import health as _health
    hvec = _health.health_vec(carries=(u, v), hist=hist, n_done=n_iter)
    return u, v, cur, n_iter, conv, hist, hvec


# nnz chunk cap for the streamed normal-equation sums, and the element
# budget for the (chunk, f²) intermediate (chunk·f² ≤ _SPARSE_BUDGET)
_SPARSE_CHUNK = 1 << 18
_SPARSE_BUDGET = 1 << 22


def _fold_in_body(vals, cols, items, lambda_, n_f, policy, top_n=0):
    """The fold-in math: per-user regularized normal equations against
    the frozen item factors, then one predict GEMM — entirely traced, so
    the serving pipeline's packed variant fuses it into the same single
    dispatch.  (value != 0) doubles as the observation mask AND the pad
    mask (pads are value-0 at the sentinel column).

    ``top_n`` > 0 ranks in the SAME program: ``lax.top_k`` fuses after
    the predict GEMM, so a recommend-top-N serve stays one dispatch and
    fetches (k, top_n) instead of the full (k, n_items) score matrix."""
    from dislib_tpu.ops import precision as px
    # weight = observed AND in-range: an out-of-range id (corrupt
    # request past the pack-time validation) becomes a no-op instead of
    # silently scoring against the clipped last item — the slot-mask
    # defense-in-depth discipline at the serving boundary
    in_range = (cols >= 0) & (cols < items.shape[0])
    w = ((vals != 0) & in_range).astype(items.dtype)
    g = items[jnp.clip(cols, 0, items.shape[0] - 1)] * w[..., None]
    a = px.peinsum("ksf,ksg->kfg", g, g, policy)           # (k, f, f)
    cnt = jnp.sum(w, axis=1)
    reg = lambda_ * jnp.maximum(cnt, 1.0)
    a = a + reg[:, None, None] * jnp.eye(n_f, dtype=a.dtype)
    b = px.peinsum("ks,ksf->kf", vals.astype(items.dtype) * w, g, policy)
    chol = jax.scipy.linalg.cho_factor(a)
    factors = jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]
    preds = px.pdot(factors, items.T, policy)              # (k, n_items)
    if top_n:
        scores, ids = lax.top_k(preds, int(top_n))
        return factors, (ids.astype(jnp.int32), scores)
    return factors, preds


# lambda_ is STATIC: it is per-model configuration (one retrace per
# fitted model), and a dynamic scalar operand would cost one
# host->device scalar transfer per served batch — the zero-transfer
# serving boundary is counter-asserted in tests/test_spmm.py
@partial(_pjit, static_argnames=("lambda_", "n_f", "policy", "top_n"),
         name="als_fold_in")
@precise
def _als_fold_in(vals, cols, items, lambda_, n_f, policy, top_n=0):
    return _fold_in_body(vals, cols, items, lambda_, n_f, policy,
                         top_n=top_n)


@partial(_pjit, static_argnames=("lambda_", "n_f", "policy", "top_n"),
         name="als_fold_in_packed")
@precise
def _als_fold_in_packed(buf, items, lambda_, n_f, policy, top_n=0):
    """Serving entry: one PACKED sparse batch — each request row is
    ``[cols | vals]`` (2·s floats, pads (0, 0)) — split and cast ON
    DEVICE so a served batch stays ONE fused dispatch.  Column ids ride
    float32 exactly below 2^24; the pipeline validates the item count."""
    s = buf.shape[1] // 2
    cols = buf[:, :s].astype(jnp.int32)
    vals = buf[:, s:]
    return _fold_in_body(vals, cols, items, lambda_, n_f, policy,
                         top_n=top_n)
