"""ALS collaborative filtering (reference: `dislib/recommendation/als` —
`_update_chunk` tasks solving per-row regularized least squares alternately
for user and item factors on a blocked sparse ratings matrix, RMSE-based
convergence; SURVEY.md §3.3).

TPU-native redesign:

- The reference alternates over the two matrix dimensions by mapping
  `_update_chunk` tasks over row blocks of R (user step) and of Rᵀ (item
  step).  Here BOTH half-steps live inside ONE jitted `lax.while_loop`
  iteration over the sharded ratings matrix: the per-user normal equations
  ``A_u = Σ_{j∈Ω_u} v_j v_jᵀ + λ n_u I`` are built for *all* users at once as
  one GEMM (``mask @ (v_f · v_g)`` reshaped to (m, f, f)) plus ``b = R @ V``
  — MXU-bound — followed by a batched Cholesky solve.  The item step is the
  same kernel on the transpose.
- Dense `Array` ratings are dense-with-mask (SURVEY §8 "Sparse support"
  fallback): entry==0 means unobserved, exactly the information the
  reference's CSR sparsity structure carries.  The ds-array padding region
  is zero by invariant, so padded rows/cols solve to λI·x=0 → zero factors
  and never perturb the observed entries.
- `SparseArray` ratings take a TRUE sparse path (`_als_fit_sparse`): the
  normal equations are segment-sums over the observed (user, item, rating)
  triplets — O(nnz·f²) work/memory, no densification — matching the
  reference's CSR-block `_update_chunk` economics.
- Convergence (|ΔRMSE| < tol, on train or held-out test ratings) is decided
  ON DEVICE inside the while_loop — host syncs once per fit, not per
  iteration (the reference syncs the RMSE scalar every iteration).
- Regularisation follows the reference's Zhou et al. weighted-λ scheme:
  λ · n_u scales with each row's observation count.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, \
    ensure_canonical as _ensure_canonical
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise
from dislib_tpu.runtime import fetch as _fetch, repad_rows as _repad_rows
from dislib_tpu.runtime import fitloop as _fitloop
from dislib_tpu.runtime import health as _health
from dislib_tpu.utils.dlog import verbose_logger
from dislib_tpu.utils.profiling import profiled_jit as _pjit


class ALS(BaseEstimator):
    """Alternating Least Squares matrix factorisation.

    Parameters (reference parity: `dislib/recommendation/als :: ALS`)
    ----------
    n_f : int, default 8
        Number of latent factors.
    lambda_ : float, default 0.065
        Regularisation strength (weighted by per-row rating counts).
    tol : float, default 1e-4
        Convergence threshold on |ΔRMSE| between iterations.
    max_iter : int, default 100
    random_state : int or None
    verbose : bool — log per-chunk RMSE under the dslib.als logger.
    arity : int — accepted and ignored (reference reduction-tree fan-in;
        reduction topology is XLA's job now).

    Attributes
    ----------
    users_ : ndarray (n_users, n_f) — user factor matrix U.
    items_ : ndarray (n_items, n_f) — item factor matrix V.
    converged_ : bool
    n_iter_ : int
    rmse_ : float — RMSE over the convergence ratings at the last iteration.
    history_ : ndarray (n_iter_,) — per-iteration held-out RMSE (SURVEY §6).
    """

    def __init__(self, n_f=8, lambda_=0.065, tol=1e-4, max_iter=100,
                 random_state=None, verbose=False, arity=48):
        self.n_f = n_f
        self.lambda_ = lambda_
        self.tol = tol
        self.max_iter = max_iter
        self.random_state = random_state
        self.verbose = verbose
        self.arity = arity

    def fit(self, x: Array, test=None, checkpoint=None, health=None):
        """Factorise the ratings matrix ``x`` (users × items, 0 = unobserved).

        ``test`` — optional held-out ratings (ndarray or ds-array with the
        same shape, 0 = unobserved) used for the convergence RMSE instead of
        the training ratings, as in the reference.
        ``checkpoint`` — optional ``FitCheckpoint``: run in `every`-iteration
        chunks, snapshot (users, items, rmse, n_iter) after each, resume from
        the snapshot on re-run (SURVEY §6 checkpoint/resume).  Between
        chunks the loop honours the preemption flag (`dislib_tpu.runtime`):
        snapshot first, then a clean ``Preempted``.  Snapshots record the
        LOGICAL factor dims, so a checkpoint written on one mesh resumes on
        a different device count (the factors are re-padded on restore —
        elastic resume).
        ``health`` — optional :class:`~dislib_tpu.runtime.HealthPolicy`;
        each chunk's kernel emits a fused health vector over the factors
        and the RMSE history.  A tripped guard rolls back to the
        last-good snapshot; the ``halve`` action additionally doubles
        ``lambda_`` per restart (the normal-equation ridge — ALS's
        damping knob against ill-conditioned solves).
        """
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        from dislib_tpu.data.sparse import SparseArray
        sparse_in = isinstance(x, SparseArray)
        if sparse_in:
            # true sparse path: the normal equations are built by
            # segment-sums over the observed (user, item, rating) triplets —
            # O(nnz·f²) work/memory instead of the dense path's O(m·n·f²)
            # mask GEMM; no densification ever happens
            rows_d, cols_d, vals = _triplets(x)
            t_trip = (rows_d, cols_d, vals) if test is None \
                else _test_triplets(test, x.shape)
        t_host = None
        if not sparse_in and test is not None:
            import scipy.sparse as sp
            if isinstance(test, SparseArray):
                t_host = np.asarray(test.collect().toarray())
            else:
                t = test.collect() if isinstance(test, Array) else test
                t_host = np.asarray(t.toarray() if sp.issparse(t) else t)
            if t_host.shape != x.shape:
                raise ValueError(f"test ratings shape {t_host.shape} != "
                                 f"ratings shape {x.shape}")
        seed = self.random_state if self.random_state is not None else 0
        box = {"x": x, "lam": float(self.lambda_), "rmse": np.inf}

        def _bind_test():
            if not sparse_in:
                box["test_p"] = box["x"]._data if t_host is None \
                    else _pad_like(t_host, box["x"])
        _bind_test()

        def rebind(mesh):
            if mesh is None:            # pre-switch: force pending chains
                box["x"].force()
                return
            box["x"] = _ensure_canonical(box["x"])
            _bind_test()

        log = verbose_logger("als", self.verbose)
        loop = _fitloop.ChunkedFitLoop(
            "als", checkpoint=checkpoint, health=health,
            max_iter=self.max_iter, carry_names=("users", "items"),
            carry_shapes=((x.shape[0], int(self.n_f)),
                          (x.shape[1], int(self.n_f))),
            elastic=None if sparse_in else rebind)

        def init(rem):
            # ALS damping: the 'halve' tier raises the per-row ridge λ·n_u
            # per attempt (ill-conditioned normal equations are the
            # numeric failure mode of the batched Cholesky solves)
            box["lam"] = float(self.lambda_) * rem.damping
            box["rmse"] = np.inf
            return _fitloop.LoopState(())   # fresh: the kernel seeds itself

        def restore(snap, rem):
            # snapshots carry the LOGICAL factor dims (m, n); the stored
            # factor arrays may be padded for a different mesh — elastic
            # resume re-pads them for THIS mesh (runtime.repad_rows)
            if "m" not in snap or "users" not in snap:
                raise ValueError(
                    "checkpoint is missing the ALS factor state — stale "
                    "or foreign snapshot")
            sm, sn = int(snap["m"]), int(snap["n"])
            if (sm, sn) != tuple(x.shape) or \
                    snap["users"].shape[1:] != (int(self.n_f),):
                raise ValueError(
                    f"checkpoint factors (users {snap['users'].shape} "
                    f"over ratings {(sm, sn)}) do not match this "
                    f"estimator/data (ratings {tuple(x.shape)}, "
                    f"n_f={self.n_f}) — stale or foreign snapshot")
            box["lam"] = float(self.lambda_) * rem.damping
            box["rmse"] = float(snap["rmse"])
            tu = x.shape[0] if sparse_in else box["x"]._data.shape[0]
            tv = x.shape[1] if sparse_in else box["x"]._data.shape[1]
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(_repad_rows(snap["users"], sm, tu))),
                 jnp.asarray(rem.perturb(_repad_rows(snap["items"], sn, tv)))),
                it=int(snap["n_iter"]),
                done=bool(snap.get("converged", False)),
                extra=float(snap["rmse"]))

        def step(st, chunk):
            state = (*st.carries, st.extra) if st.carries else None
            if sparse_in:
                u, v, rmse_dev, n_done, conv, hist, hvec = _als_fit_sparse(
                    rows_d, cols_d, vals, *t_trip, x.shape[0], x.shape[1],
                    int(self.n_f), box["lam"], float(self.tol),
                    chunk, int(seed), init_state=state)
            else:
                u, v, rmse_dev, n_done, conv, hist, hvec = _als_fit(
                    box["x"]._data, box["test_p"], x.shape, int(self.n_f),
                    box["lam"], float(self.tol), chunk, int(seed),
                    init_state=state)

            def commit():
                # deferred scalar syncs: the watchdogged hvec read stays
                # the chunk's first force point
                box["rmse"] = float(rmse_dev)
                it = st.it + int(n_done)
                log.info("iter %d: rmse=%.6g", it, box["rmse"])
                return _fitloop.LoopState((u, v), it, bool(conv),
                                          extra=box["rmse"])

            return _fitloop.ChunkOutcome(
                commit, hvec=hvec,
                history=lambda: _fetch(hist)[: int(n_done)])

        def snapshot(st):
            # the factors are DONATED to the next chunk's kernel call
            # (their HBM is reused in place), so their device->host copies
            # must land before that dispatch: fetch blocking, and offload
            # only the checksum+write to the snapshot worker
            return {"users": _fetch(st.carries[0]),
                    "items": _fetch(st.carries[1]),
                    "m": x.shape[0], "n": x.shape[1],
                    "rmse": st.extra, "n_iter": st.it, "converged": st.done}

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=snapshot)
        u, v = st.carries
        m, n = x.shape
        self.users_ = np.asarray(jax.device_get(u))[:m]
        self.items_ = np.asarray(jax.device_get(v))[:n]
        self.rmse_ = float(box["rmse"])
        self.n_iter_ = st.it
        self.converged_ = st.done
        self.history_ = np.asarray(loop.history, dtype=np.float64)
        self.fit_info_ = loop.info
        return self

    # async trial protocol (SURVEY §4.5): the no-test, no-checkpoint fit is
    # one jitted while_loop; the handle is its device output tuple.  Sparse
    # inputs read their triplets (input prep, not fit results) at dispatch.
    def _fit_async(self, x, y=None):
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        from dislib_tpu.data.sparse import SparseArray
        seed = self.random_state if self.random_state is not None else 0
        if isinstance(x, SparseArray):
            rows_d, cols_d, vals = _triplets(x)
            out = _als_fit_sparse(rows_d, cols_d, vals, rows_d, cols_d, vals,
                                  x.shape[0], x.shape[1], int(self.n_f),
                                  float(self.lambda_), float(self.tol),
                                  self.max_iter, int(seed))
        else:
            out = _als_fit(x._data, x._data, x.shape, int(self.n_f),
                           float(self.lambda_), float(self.tol),
                           self.max_iter, int(seed))
        return (out, x.shape)

    def _fit_finalize(self, state):
        if state is None:
            return
        (u, v, rmse, n_iter, conv, hist, _), (m, n) = state
        self.users_ = np.asarray(jax.device_get(u))[:m]
        self.items_ = np.asarray(jax.device_get(v))[:n]
        self.rmse_ = float(rmse)
        self.n_iter_ = int(n_iter)
        self.converged_ = bool(conv)
        self.history_ = np.asarray(
            jax.device_get(hist), dtype=np.float64)[: self.n_iter_]

    def predict_user(self, user_id: int) -> np.ndarray:
        """Predicted ratings for every item for one user (reference parity)."""
        self._check_fitted()
        if not 0 <= user_id < self.users_.shape[0]:
            raise IndexError(f"user_id {user_id} out of range")
        return self.users_[user_id] @ self.items_.T

    def _check_fitted(self):
        if not hasattr(self, "users_"):
            raise RuntimeError("ALS is not fitted")


def _test_triplets(test, want_shape):
    """Held-out ratings → (rows, cols, vals) triplets with 0 = unobserved;
    accepts SparseArray, scipy sparse, ds-array, or ndarray without ever
    densifying a sparse input."""
    from dislib_tpu.data.sparse import SparseArray
    import scipy.sparse as sp
    t = test
    if isinstance(t, Array) and not isinstance(t, SparseArray):
        t = t.collect()
    if not (isinstance(t, SparseArray) or sp.issparse(t)):
        t = np.asarray(t)
    if tuple(t.shape) != tuple(want_shape):
        raise ValueError(f"test ratings shape {tuple(t.shape)} != "
                         f"ratings shape {tuple(want_shape)}")
    if isinstance(t, SparseArray):
        return _triplets(t)
    if sp.issparse(t):
        coo = t.tocoo()
        keep = coo.data != 0
        return (jnp.asarray(coo.row[keep], jnp.int32),
                jnp.asarray(coo.col[keep], jnp.int32),
                jnp.asarray(coo.data[keep], jnp.float32))
    tr, tc = np.nonzero(t)
    return (jnp.asarray(tr, jnp.int32), jnp.asarray(tc, jnp.int32),
            jnp.asarray(t[tr, tc], jnp.float32))


def _triplets(x):
    """(rows, cols, vals) int32/f32 device triplets of a SparseArray with
    explicit zeros dropped — 0 means unobserved everywhere in ALS, matching
    the dense-with-mask path, so an explicitly-stored 0 must not become an
    observed rating."""
    idx = np.asarray(jax.device_get(x._bcoo.indices))
    val = np.asarray(jax.device_get(x._bcoo.data))
    keep = val != 0
    return (jnp.asarray(idx[keep, 0], jnp.int32),
            jnp.asarray(idx[keep, 1], jnp.int32),
            jnp.asarray(val[keep], jnp.float32))


def _pad_like(t: np.ndarray, x: Array):
    """Pad host ratings to x's padded device shape (zeros outside logical)."""
    out = np.zeros(x._data.shape, dtype=x._data.dtype)
    out[: t.shape[0], : t.shape[1]] = t
    return jnp.asarray(out)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _solve_factors(r, mask, v, lambda_, n_f):
    """Per-row regularized LS for all rows at once (the `_update_chunk` role).

    A = einsum('mn,nf,ng->mfg', mask, v, v) — XLA lowers this to one GEMM
    ``mask @ (v ⊗ v)`` of shape (m, n)×(n, f²); b = r @ v is a second GEMM.
    Batched Cholesky solve finishes the normal equations.
    """
    counts = jnp.sum(mask, axis=1)
    b = r @ v                                            # (m, f)
    vv = (v[:, :, None] * v[:, None, :]).reshape(v.shape[0], n_f * n_f)
    a = (mask @ vv).reshape(-1, n_f, n_f)
    reg = lambda_ * jnp.maximum(counts, 1.0)
    a = a + reg[:, None, None] * jnp.eye(n_f, dtype=r.dtype)
    chol = jax.scipy.linalg.cho_factor(a)
    return jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]


# init_state (the resumed/chunked factor carries) is DONATED: XLA aliases
# u0/v0 to the output factors and reuses their HBM in place instead of
# double-buffering the two largest arrays of the fit (round-7 perf PR).
# Callers never reuse a passed init_state afterwards.
@partial(_pjit, static_argnames=("shape", "n_f", "max_iter"),
         donate_argnames=("init_state",), name="als_fit")
@precise
def _als_fit(rp, test_p, shape, n_f, lambda_, tol, max_iter, seed,
             init_state=None):
    rp = lax.with_sharding_constraint(rp, _mesh.data_sharding())
    mask = (rp != 0).astype(rp.dtype)
    tmask = (test_p != 0).astype(rp.dtype)
    key = jax.random.PRNGKey(seed)
    ku, kv = jax.random.split(key)
    # reference seeds item factors from the per-item mean rating; uniform
    # init scaled to the mean magnitude behaves equivalently
    u0 = jax.random.uniform(ku, (rp.shape[0], n_f), rp.dtype)
    v0 = jax.random.uniform(kv, (rp.shape[1], n_f), rp.dtype)
    prev0 = jnp.asarray(jnp.inf, rp.dtype)
    if init_state is not None:                 # mid-fit checkpoint resume
        u0, v0, prev0 = init_state
        prev0 = jnp.asarray(prev0, rp.dtype)

    def rmse(u, v):
        se = ((u @ v.T - test_p) * tmask) ** 2
        return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(tmask), 1.0))

    def step(carry):
        u, v, prev_rmse, it, _, hist = carry
        u = _solve_factors(rp, mask, v, lambda_, n_f)
        v = _solve_factors(rp.T, mask.T, u, lambda_, n_f)
        cur = rmse(u, v)
        conv = jnp.abs(prev_rmse - cur) < tol
        return u, v, cur, it + 1, conv, hist.at[it].set(cur)

    def cond(carry):
        _, _, _, it, conv, _ = carry
        return (it < max_iter) & (~conv)

    init = (u0, v0, prev0, jnp.int32(0), jnp.asarray(False),
            jnp.zeros((max_iter,), rp.dtype))
    u, v, cur, n_iter, conv, hist = lax.while_loop(cond, step, init)
    # fused health vector — same program, zero extra dispatches
    from dislib_tpu.runtime import health as _health
    hvec = _health.health_vec(carries=(u, v), hist=hist, n_done=n_iter)
    return u, v, cur, n_iter, conv, hist, hvec


@partial(_pjit, static_argnames=("m", "n", "n_f", "max_iter"),
         donate_argnames=("init_state",), name="als_fit_sparse")
@precise
def _als_fit_sparse(rows, cols, vals, trows, tcols, tvals, m, n, n_f,
                    lambda_, tol, max_iter, seed, init_state=None):
    """ALS over observed triplets only: per-row normal equations assembled
    with `segment_sum` over the nnz entries (the reference's CSR-block
    `_update_chunk` role, collapsed to two segment reductions + one batched
    Cholesky per half-step).  The (chunk, f²) outer-product intermediate is
    streamed over nnz chunks so peak memory is O(chunk·f²) + O((m+n)·f²),
    never O(nnz·f²).  Device placement: single-program (factors replicated);
    the per-entry gathers/scatters don't shard cleanly across a mesh — the
    recorded scale ceiling is (m+n)·f² factor storage per device."""
    key = jax.random.PRNGKey(seed)
    ku, kv = jax.random.split(key)
    u0 = jax.random.uniform(ku, (m, n_f), vals.dtype)
    v0 = jax.random.uniform(kv, (n, n_f), vals.dtype)
    prev0 = jnp.asarray(jnp.inf, vals.dtype)
    if init_state is not None:                 # mid-fit checkpoint resume
        u0, v0, prev0 = init_state
        prev0 = jnp.asarray(prev0, vals.dtype)
    eye = jnp.eye(n_f, dtype=vals.dtype)

    nnz = vals.shape[0]
    # chunk scales inversely with f² so the (chunk, f²) outer-product
    # intermediate stays within a fixed element budget at any factor count;
    # max(1, ...) keeps the nnz == 0 edge (no observed ratings → A = λI,
    # zero factors, rmse 0) well-formed
    chunk = max(1, min(nnz, _SPARSE_CHUNK, _SPARSE_BUDGET // (n_f * n_f)))
    n_chunks = -(-nnz // chunk)
    pad = n_chunks * chunk - nnz
    # pad triplets with (row 0, col 0, val 0) + zero weight so they add 0
    rows_p = jnp.pad(rows, (0, pad))
    cols_p = jnp.pad(cols, (0, pad))
    vals_p = jnp.pad(vals, (0, pad))
    w_p = jnp.pad(jnp.ones_like(vals), (0, pad))

    def solve(seg_c, other, idx_c, nseg):
        """Stream the normal-equation sums over nnz chunks: seg_c/idx_c are
        (n_chunks, chunk) row/col ids, `other` the opposite factor matrix."""

        def body(acc, cx):
            sc, ic, vc, wc = cx
            g = other[ic] * wc[:, None]               # pad rows → all-zero
            b = jax.ops.segment_sum(vc[:, None] * g, sc, num_segments=nseg)
            outer = (g[:, :, None] * g[:, None, :]).reshape(chunk, n_f * n_f)
            a = jax.ops.segment_sum(outer, sc, num_segments=nseg)
            cnt = jax.ops.segment_sum(wc, sc, num_segments=nseg)
            return (acc[0] + a, acc[1] + b, acc[2] + cnt), None

        acc0 = (jnp.zeros((nseg, n_f * n_f), vals.dtype),
                jnp.zeros((nseg, n_f), vals.dtype),
                jnp.zeros((nseg,), vals.dtype))
        (a, b, counts), _ = lax.scan(
            body, acc0,
            (seg_c.reshape(n_chunks, chunk), idx_c.reshape(n_chunks, chunk),
             vals_p.reshape(n_chunks, chunk), w_p.reshape(n_chunks, chunk)))
        a = a.reshape(nseg, n_f, n_f)
        # unobserved rows: A = λ·I, b = 0 → zero factors (harmless)
        reg = lambda_ * jnp.maximum(counts, 1.0)
        a = a + reg[:, None, None] * eye
        chol = jax.scipy.linalg.cho_factor(a)
        return jax.scipy.linalg.cho_solve(chol, b[..., None])[..., 0]

    def rmse(u, v):
        pred = jnp.sum(u[trows] * v[tcols], axis=1)
        return jnp.sqrt(jnp.sum((pred - tvals) ** 2)
                        / jnp.maximum(tvals.shape[0], 1))

    def step(carry):
        u, v, prev_rmse, it, _, hist = carry
        u = solve(rows_p, v, cols_p, m)
        v = solve(cols_p, u, rows_p, n)
        cur = rmse(u, v)
        conv = jnp.abs(prev_rmse - cur) < tol
        return u, v, cur, it + 1, conv, hist.at[it].set(cur)

    def cond(carry):
        _, _, _, it, conv, _ = carry
        return (it < max_iter) & (~conv)

    init = (u0, v0, prev0, jnp.int32(0), jnp.asarray(False),
            jnp.zeros((max_iter,), vals.dtype))
    u, v, cur, n_iter, conv, hist = lax.while_loop(cond, step, init)
    # fused health vector — same program, zero extra dispatches
    from dislib_tpu.runtime import health as _health
    hvec = _health.health_vec(carries=(u, v), hist=hist, n_done=n_iter)
    return u, v, cur, n_iter, conv, hist, hvec


# nnz chunk cap for the streamed normal-equation sums, and the element
# budget for the (chunk, f²) intermediate (chunk·f² ≤ _SPARSE_BUDGET)
_SPARSE_CHUNK = 1 << 18
_SPARSE_BUDGET = 1 << 22
